"""Per-segment zone maps (SURVEY.md §2 metadata "stats"): filters that
provably cannot match a segment prune it before dispatch — and pruning must
never change results."""

import numpy as np
import pandas as pd
import pytest

import spark_druid_olap_tpu as sd


@pytest.fixture(scope="module")
def clustered():
    """Data CLUSTERED by key: segment i holds keys [i*25, (i+1)*25) — the
    layout where zone maps bite (time-sorted/partitioned ingest)."""
    n, segs = 40_000, 4
    keys = np.sort(np.random.default_rng(5).integers(0, 100, n))
    vals = np.random.default_rng(6).random(n).astype(np.float32) * 100
    cities = np.array([f"c{k:03d}" for k in keys], dtype=object)
    ctx = sd.TPUOlapContext()
    ctx.register_table(
        "cl",
        {"city": cities, "k": keys, "v": vals},
        dimensions=["city", "k"],
        metrics=["v"],
        rows_per_segment=n // segs,
    )
    df = pd.DataFrame(
        {"city": cities, "k": keys.astype(np.int64),
         "v": vals.astype(np.float64)}
    )
    return ctx, df


def test_selector_prunes_to_one_segment(clustered):
    ctx, df = clustered
    ds = ctx.catalog.get("cl")
    target = "c010"  # lives only in the first quarter of the keys
    eng = ctx.engine
    segs = eng._segments_in_scope(
        ctx.plan_sql(
            f"SELECT count(*) AS n FROM cl WHERE city = '{target}'"
        ).query,
        ds,
    )
    assert len(segs) < len(ds.segments)
    got = ctx.sql(f"SELECT count(*) AS n FROM cl WHERE city = '{target}'")
    assert int(got["n"].iloc[0]) == int((df.city == target).sum())


def test_absent_value_prunes_everything(clustered):
    ctx, df = clustered
    got = ctx.sql("SELECT count(*) AS n FROM cl WHERE city = 'nope'")
    assert int(got["n"].iloc[0]) == 0
    got2 = ctx.sql(
        "SELECT count(*) AS n FROM cl WHERE city IN ('nope', 'nada')"
    )
    assert int(got2["n"].iloc[0]) == 0


def test_numeric_bound_prunes_and_stays_exact(clustered):
    ctx, df = clustered
    # v is uniform across segments -> no pruning from v; k is clustered
    for sql, mask in [
        ("SELECT sum(v) AS s, count(*) AS n FROM cl WHERE v > 150",
         df.v > 150),  # beyond global max: zero rows
        ("SELECT sum(v) AS s, count(*) AS n FROM cl WHERE v <= 50",
         df.v <= 50),
    ]:
        got = ctx.sql(sql)
        want_n = int(mask.sum())
        assert int(got["n"].iloc[0]) == want_n
        if want_n:
            np.testing.assert_allclose(
                float(got["s"].iloc[0]), df.v[mask].sum(), rtol=2e-5
            )


def test_in_filter_parity_under_pruning(clustered):
    ctx, df = clustered
    vals = ["c005", "c050", "c095"]  # spans three different segments
    frag = ", ".join(f"'{v}'" for v in vals)
    got = ctx.sql(
        f"SELECT city, count(*) AS n FROM cl WHERE city IN ({frag}) "
        "GROUP BY city ORDER BY city"
    )
    want = (
        df[df.city.isin(vals)]
        .groupby("city", as_index=False)
        .size()
        .rename(columns={"size": "n"})
        .sort_values("city")
    )
    assert list(got["city"]) == list(want["city"])
    np.testing.assert_array_equal(got["n"].values, want["n"].values)


def test_stats_survive_persistence(tmp_path, clustered):
    ctx, df = clustered
    from spark_druid_olap_tpu.catalog.persist import (
        load_datasource,
        save_datasource,
    )

    d = save_datasource(ctx.catalog.get("cl"), str(tmp_path / "cl"))
    ds2, _ = load_datasource(d)
    assert all(s.stats for s in ds2.segments)
    s0 = ds2.segments[0]
    assert s0.stats["k"][0] == 0.0  # first segment holds the smallest keys


def test_sort_by_ingest_enables_pruning():
    """register_table(sort_by=...): unsorted input gets clustered at ingest
    so zone maps prune — and results are identical to the unsorted table."""
    rng = np.random.default_rng(11)
    n = 20_000
    key = rng.integers(0, 100, n)  # UNSORTED
    val = rng.random(n).astype(np.float32)
    plain = sd.TPUOlapContext()
    plain.register_table(
        "u", {"k": key, "v": val}, dimensions=["k"], metrics=["v"],
        rows_per_segment=n // 4,
    )
    sorted_ctx = sd.TPUOlapContext()
    sorted_ctx.register_table(
        "u", {"k": key, "v": val}, dimensions=["k"], metrics=["v"],
        rows_per_segment=n // 4, sort_by=["k"],
    )
    sql = "SELECT count(*) AS n, sum(v) AS s FROM u WHERE k = 7"
    a = plain.sql(sql)
    b = sorted_ctx.sql(sql)
    assert int(a["n"].iloc[0]) == int(b["n"].iloc[0])
    np.testing.assert_allclose(
        float(a["s"].iloc[0]), float(b["s"].iloc[0]), rtol=2e-5
    )
    # the sorted table's scope collapses to a single segment
    ds = sorted_ctx.catalog.get("u")
    rw = sorted_ctx.plan_sql(sql)
    assert len(sorted_ctx.engine._segments_in_scope(rw.query, ds)) == 1
    assert len(
        plain.engine._segments_in_scope(
            plain.plan_sql(sql).query, plain.catalog.get("u")
        )
    ) == 4


def test_sort_by_unknown_column_rejected():
    ctx = sd.TPUOlapContext()
    with pytest.raises(ValueError, match="unknown columns"):
        ctx.register_table(
            "x", {"a": np.arange(10)}, dimensions=["a"], sort_by=["nope"]
        )


def test_distributed_zone_map_pruning():
    """The SPMD mesh path prunes segments by zone maps too — and stays
    exact."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from spark_druid_olap_tpu.parallel.distributed import DistributedEngine
    from spark_druid_olap_tpu.parallel.mesh import make_mesh

    n, segs = 32_000, 4
    keys = np.sort(np.random.default_rng(15).integers(0, 100, n))
    vals = np.random.default_rng(16).random(n).astype(np.float32)
    ctx = sd.TPUOlapContext()
    ctx.register_table(
        "dcl", {"k": keys, "v": vals},
        dimensions=["k"], metrics=["v"], rows_per_segment=n // segs,
    )
    ds = ctx.catalog.get("dcl")
    rw = ctx.plan_sql("SELECT count(*) AS n, sum(v) AS s FROM dcl WHERE k = 7")
    eng = DistributedEngine(mesh=make_mesh(n_data=8))
    got = eng.execute(rw.query, ds)
    df = pd.DataFrame({"k": keys, "v": vals.astype(np.float64)})
    want_n = int((df.k == 7).sum())
    assert int(got["n"].iloc[0]) == want_n
    np.testing.assert_allclose(
        float(got["s"].iloc[0]), df.v[df.k == 7].sum(), rtol=2e-5
    )
    # pruning actually engaged: post-prune metrics cover ONE segment, and
    # the shard cache holds only that segment's rows
    assert eng.last_metrics.segments == 1
    assert eng.last_metrics.rows_scanned == ds.segments[0].num_rows
    assert eng.last_metrics.rows_scanned < ds.num_rows


@pytest.fixture(scope="module")
def metric_clustered():
    """A table whose METRIC m is clustered across segments (m rises with
    row order), so numeric-bound zone maps prune — the canvas for the
    virtual-column shadowing cases (metric shadowing is value-space and
    therefore supported end to end)."""
    n, segs = 40_000, 4
    m = np.sort(
        np.random.default_rng(9).integers(0, 100, n)
    ).astype(np.float32)
    cities = np.array([f"g{int(x) // 20}" for x in m], dtype=object)
    v = np.random.default_rng(10).random(n).astype(np.float32)
    ctx = sd.TPUOlapContext()
    ctx.register_table(
        "mcl",
        {"city": cities, "m": m, "v": v},
        dimensions=["city"],
        metrics=["m", "v"],
        rows_per_segment=n // segs,
    )
    df = pd.DataFrame(
        {"city": cities, "m": m.astype(np.float64),
         "v": v.astype(np.float64)}
    )
    return ctx, df


def _shadow_query():
    from spark_druid_olap_tpu.models.aggregations import DoubleSum
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec
    from spark_druid_olap_tpu.models.filters import Bound
    from spark_druid_olap_tpu.models.query import GroupByQuery, VirtualColumn
    from spark_druid_olap_tpu.plan.expr import Literal, col

    # "m" is redefined as 100 - m: the filter m < 10 selects the HIGH
    # physical values, which live in the LAST segment
    return GroupByQuery(
        datasource="mcl",
        dimensions=(DimensionSpec("city"),),
        aggregations=(DoubleSum("s", "v"),),
        virtual_columns=(
            VirtualColumn("m", Literal(100.0) - col("m")),
        ),
        filter=Bound("m", upper="10", ordering="numeric"),
    )


def test_virtual_column_shadow_disables_pruning(metric_clustered):
    """A virtual column SHADOWING a physical metric: the filter evaluates
    against the virtual values at execution, so pruning it against the
    physical column's zone map would silently drop live segments
    (round-2 advisor finding) — and the whole query must run correctly."""
    import dataclasses

    from spark_druid_olap_tpu.models.filters import Bound

    ctx, df = metric_clustered
    ds = ctx.catalog.get("mcl")
    eng = ctx.engine
    q = _shadow_query()
    segs = eng._segments_in_scope(q, ds)
    assert len(segs) == len(ds.segments)  # no pruning on shadowed name
    # the same bound WITHOUT the virtual column does prune
    q2 = dataclasses.replace(q, virtual_columns=())
    assert len(eng._segments_in_scope(q2, ds)) < len(ds.segments)
    # end-to-end: correct rows (virtual m < 10 means physical m > 90)
    got = eng.execute(q, ds)
    w = df[100.0 - df.m <= 10].groupby("city")["v"].sum()
    got_by = {r["city"]: float(r["s"]) for _, r in got.iterrows()}
    assert set(got_by) == set(w.index)
    for city, s in w.items():
        np.testing.assert_allclose(got_by[city], s, rtol=2e-5)


def test_vcol_shadowing_dict_dimension_rejected(clustered):
    """Shadowing a DICTIONARY-ENCODED dimension cannot be honored soundly
    (filters/groupings compile into code space) — clear refusal, not a
    wrong answer."""
    from spark_druid_olap_tpu.models.aggregations import DoubleSum
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec
    from spark_druid_olap_tpu.models.filters import Bound
    from spark_druid_olap_tpu.models.query import GroupByQuery, VirtualColumn
    from spark_druid_olap_tpu.plan.expr import Literal, col

    ctx, _ = clustered
    ds = ctx.catalog.get("cl")
    q = GroupByQuery(
        datasource="cl",
        dimensions=(DimensionSpec("city"),),
        aggregations=(DoubleSum("s", "v"),),
        virtual_columns=(
            VirtualColumn("k", Literal(100) - col("k"), dtype="long"),
        ),
        filter=Bound("k", upper="10", ordering="numeric"),
    )
    with pytest.raises(ValueError, match="shadow"):
        ctx.engine.execute(q, ds)


def test_sort_by_encoded_dims_nulls_last():
    """sort_by over PRE-ENCODED dimension codes (caller-supplied dicts):
    null codes are negative and must still cluster LAST (round-2 advisor
    finding — raw code order put them first)."""
    from spark_druid_olap_tpu.catalog.segment import DimensionDict

    c = sd.TPUOlapContext()
    codes = np.array([1, -1, 0, 1, -1], dtype=np.int32)
    c.register_table(
        "enc",
        {"c": codes, "v": np.arange(5, dtype=np.float32)},
        dimensions=["c"],
        metrics=["v"],
        dicts={"c": DimensionDict(values=("a", "b"))},
        sort_by=["c"],
        rows_per_segment=2,
    )
    ds = c.catalog.get("enc")
    phys = np.concatenate(
        [np.asarray(s.dims["c"])[s.valid] for s in ds.segments]
    )
    nulls = phys < 0
    assert not nulls[:3].any() and nulls[3:].all()
    assert list(phys[:3]) == sorted(phys[:3])


def test_distributed_vcol_shadow_disables_pruning(metric_clustered):
    """Review finding: the mesh path must apply the same virtual-column
    shadow rule as the local engine — a shadowed filter name must not
    prune against physical stats."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from spark_druid_olap_tpu.parallel.distributed import DistributedEngine
    from spark_druid_olap_tpu.parallel.mesh import make_mesh

    ctx, df = metric_clustered
    ds = ctx.catalog.get("mcl")
    q = _shadow_query()
    eng = DistributedEngine(mesh=make_mesh(n_data=8))
    got = eng.execute(q, ds)
    # filter selects virtual m < 10 i.e. physical m > 90 — the LAST
    # segment's rows.  With wrong pruning those segments vanish -> empty.
    w = df[100.0 - df.m <= 10].groupby("city")["v"].sum()
    assert eng.last_metrics.segments == len(ds.segments)  # nothing pruned
    got_by = {r["city"]: float(r["s"]) for _, r in got.iterrows()}
    assert set(got_by) == set(w.index)
    for city, s in w.items():
        np.testing.assert_allclose(got_by[city], s, rtol=2e-5)


def test_nested_and_or_conjuncts_prune(clustered):
    """The planner builds Ands PAIRWISE and year-style disjunctions as
    Or(Bound, Bound): both shapes must still prune (round-3 fix — the SSB
    q1/q4 latency class depends on it)."""
    ctx, df = clustered
    ds = ctx.catalog.get("cl")
    eng = ctx.engine
    # nested And: (k = 7 AND v > 0) AND v < 1000 — k=7 lives in segment 0
    rw = ctx.plan_sql(
        "SELECT count(*) AS n FROM cl WHERE k = 7 AND v > 0 AND v < 1000"
    )
    assert len(eng._segments_in_scope(rw.query, ds)) == 1
    # Or of bounds on the clustered key: only the segments holding 7 or 80
    rw2 = ctx.plan_sql(
        "SELECT count(*) AS n FROM cl WHERE k = 7 OR k = 80"
    )
    segs2 = eng._segments_in_scope(rw2.query, ds)
    assert len(segs2) == 2
    got = ctx.sql("SELECT count(*) AS n FROM cl WHERE k = 7 OR k = 80")
    assert int(got["n"].iloc[0]) == int(((df.k == 7) | (df.k == 80)).sum())
