"""Differential parity: TPU-path GroupBy vs float64 numpy/pandas oracle.

SURVEY.md §4 implication: the reference asserted "plan contains DruidQuery"
plus result parity vs un-accelerated Spark; our analog is engine results vs a
trivially-correct pandas groupby on the same columns — exact for counts and
min/max, tight rtol for float sums (blocked f32 matmul vs f64 sequential)."""

import numpy as np
import pandas as pd
import pytest

from spark_druid_olap_tpu.exec.engine import Engine
from spark_druid_olap_tpu.models.aggregations import (
    Count,
    DoubleMax,
    DoubleMin,
    DoubleSum,
    ExpressionAgg,
    FilteredAgg,
)
from spark_druid_olap_tpu.models.dimensions import DimensionSpec
from spark_druid_olap_tpu.models.filters import Bound, InFilter, Selector
from spark_druid_olap_tpu.models.query import GroupByQuery
from spark_druid_olap_tpu.plan.expr import col

_MS_DAY = 86_400_000


def _oracle(cols, mask, by, aggspec):
    df = pd.DataFrame({k: np.asarray(v) for k, v in cols.items()})
    if mask is not None:
        df = df[mask]
    g = df.groupby(by, sort=True)
    return g.agg(**aggspec).reset_index()


@pytest.mark.parametrize("strategy", ["dense", "segment"])
def test_tpch_q1_parity(lineitem_ds, lineitem_cols, strategy):
    """TPC-H Q1 (BASELINE config #1): filter + 2-dim groupby, sums of raw and
    derived measures, count."""
    cutoff = (np.datetime64("1998-09-02").astype("datetime64[D]").astype(int) + 1) * _MS_DAY
    q = GroupByQuery(
        datasource="tpch",
        dimensions=(
            DimensionSpec("l_returnflag"),
            DimensionSpec("l_linestatus"),
        ),
        aggregations=(
            DoubleSum("sum_qty", "l_quantity"),
            DoubleSum("sum_base_price", "l_extendedprice"),
            ExpressionAgg(
                "sum_disc_price",
                col("l_extendedprice") * (1 - col("l_discount")),
            ),
            ExpressionAgg(
                "sum_charge",
                col("l_extendedprice") * (1 - col("l_discount")) * (1 + col("l_tax")),
            ),
            Count("count_order"),
        ),
        filter=Bound("l_shipdate", upper=str(cutoff), ordering="numeric"),
        limit_spec=None,
    )
    got = Engine(strategy=strategy).execute(q, lineitem_ds)
    got = got.sort_values(["l_returnflag", "l_linestatus"]).reset_index(drop=True)

    c = {k: np.asarray(v) for k, v in lineitem_cols.items()}
    mask = c["l_shipdate"] <= cutoff
    df = pd.DataFrame({k: v[mask] for k, v in c.items()})
    df["disc_price"] = df.l_extendedprice.astype(np.float64) * (1 - df.l_discount)
    df["charge"] = df["disc_price"] * (1 + df.l_tax)
    want = (
        df.groupby(["l_returnflag", "l_linestatus"], sort=True)
        .agg(
            sum_qty=("l_quantity", "sum"),
            sum_base_price=("l_extendedprice", "sum"),
            sum_disc_price=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            count_order=("l_quantity", "size"),
        )
        .reset_index()
    )
    assert list(got.l_returnflag) == list(want.l_returnflag)
    assert list(got.l_linestatus) == list(want.l_linestatus)
    np.testing.assert_array_equal(got.count_order, want.count_order)
    for col_ in ["sum_qty", "sum_base_price", "sum_disc_price", "sum_charge"]:
        np.testing.assert_allclose(got[col_], want[col_], rtol=2e-5)


def test_min_max_and_filtered_agg(lineitem_ds, lineitem_cols):
    q = GroupByQuery(
        datasource="tpch",
        dimensions=(DimensionSpec("l_returnflag"),),
        aggregations=(
            DoubleMin("min_price", "l_extendedprice"),
            DoubleMax("max_price", "l_extendedprice"),
            FilteredAgg(
                filter=Selector("l_linestatus", "O"),
                aggregator=Count("open_count"),
            ),
            Count("n"),
        ),
    )
    got = Engine().execute(q, lineitem_ds).sort_values("l_returnflag")
    c = lineitem_cols
    df = pd.DataFrame(
        {
            "f": c["l_returnflag"],
            "s": c["l_linestatus"],
            "p": np.asarray(c["l_extendedprice"], dtype=np.float64),
        }
    )
    want = (
        df.groupby("f", sort=True)
        .agg(
            min_price=("p", "min"),
            max_price=("p", "max"),
            n=("p", "size"),
        )
        .reset_index()
    )
    want_open = df[df.s == "O"].groupby("f").size()
    np.testing.assert_array_equal(got.n, want.n)
    np.testing.assert_allclose(got.min_price, want.min_price, rtol=1e-6)
    np.testing.assert_allclose(got.max_price, want.max_price, rtol=1e-6)
    np.testing.assert_array_equal(
        got.open_count, [int(want_open.get(f, 0)) for f in want.f]
    )


def test_in_filter_and_no_dims(lineitem_ds, lineitem_cols):
    q = GroupByQuery(
        datasource="tpch",
        dimensions=(),
        aggregations=(Count("n"), DoubleSum("s", "l_quantity")),
        filter=InFilter("l_returnflag", ("A", "R")),
    )
    got = Engine().execute(q, lineitem_ds)
    c = lineitem_cols
    m = np.isin(np.asarray(c["l_returnflag"], dtype=object), ["A", "R"])
    assert int(got.n[0]) == int(m.sum())
    np.testing.assert_allclose(
        got.s[0], np.asarray(c["l_quantity"], np.float64)[m].sum(), rtol=2e-5
    )


def test_interval_pushdown_prunes(lineitem_ds, lineitem_cols):
    c = lineitem_cols
    t = np.asarray(c["l_shipdate"])
    lo, hi = int(np.quantile(t, 0.4)), int(np.quantile(t, 0.6))
    q = GroupByQuery(
        datasource="tpch",
        dimensions=(DimensionSpec("l_linestatus"),),
        aggregations=(Count("n"),),
        intervals=((lo, hi),),
    )
    got = Engine().execute(q, lineitem_ds).sort_values("l_linestatus")
    m = (t >= lo) & (t < hi)
    want = (
        pd.Series(np.asarray(c["l_linestatus"], dtype=object)[m])
        .value_counts()
        .sort_index()
    )
    np.testing.assert_array_equal(got.n, want.values)


def test_execute_groupby_batch_sparse_matches_serial():
    """Batch execution over the SPARSE path (deferred overflow checks,
    capacity-rung logic at resolve time) must match serial execution.  On
    CPU only strategy='sparse' routes here (auto self-upgrades on TPU
    backends only)."""
    import numpy as np
    import pandas as pd

    from spark_druid_olap_tpu.catalog.segment import (
        DimensionDict,
        build_datasource,
    )
    from spark_druid_olap_tpu.exec.engine import Engine
    from spark_druid_olap_tpu.models.aggregations import Count, DoubleSum
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec
    from spark_druid_olap_tpu.models.filters import InFilter
    from spark_druid_olap_tpu.models.query import GroupByQuery

    rng = np.random.default_rng(17)
    n = 30_000
    cols = {
        "a": rng.integers(0, 300, n),
        "b": rng.integers(0, 300, n),
        "v": rng.random(n).astype(np.float32),
    }
    ds = build_datasource(
        "bts", cols, dimension_cols=["a", "b"], metric_cols=["v"],
        rows_per_segment=n // 2,
        dicts={
            "a": DimensionDict(values=tuple(range(300))),
            "b": DimensionDict(values=tuple(range(300))),
        },
    )
    aggs = (Count("n"), DoubleSum("s", "v"))
    queries = [
        # sparse-eligible: G = 300*300 >> SCATTER_CUTOVER, with a filter
        GroupByQuery(datasource="bts",
                     dimensions=(DimensionSpec("a"), DimensionSpec("b")),
                     aggregations=aggs,
                     filter=InFilter("a", tuple(range(50)))),
        # low-G: resolves through the normal kernel even under 'sparse'
        GroupByQuery(datasource="bts", dimensions=(DimensionSpec("a"),),
                     aggregations=aggs),
        # sparse-eligible, unfiltered (no compaction tier)
        GroupByQuery(datasource="bts",
                     dimensions=(DimensionSpec("a"), DimensionSpec("b")),
                     aggregations=aggs),
    ]
    want = [Engine(strategy="sparse").execute(q, ds) for q in queries]
    got = Engine(strategy="sparse").execute_groupby_batch(queries, ds)
    for w, g in zip(want, got):
        pd.testing.assert_frame_equal(
            w.reset_index(drop=True), g.reset_index(drop=True)
        )


def test_execute_groupby_batch_matches_serial():
    """The pipelined batch path (dispatch-all, resolve-all — what a CUBE
    expansion uses) must return exactly what serial execution returns, for
    a mix of dense and filtered queries (all dense on CPU CI; the sparse
    variant is covered by test_execute_groupby_batch_sparse_matches_serial)."""
    import numpy as np

    from spark_druid_olap_tpu.catalog.segment import (
        DimensionDict,
        build_datasource,
    )
    from spark_druid_olap_tpu.exec.engine import Engine
    from spark_druid_olap_tpu.models.aggregations import Count, DoubleSum
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec
    from spark_druid_olap_tpu.models.filters import InFilter
    from spark_druid_olap_tpu.models.query import GroupByQuery

    rng = np.random.default_rng(9)
    n = 30_000
    cols = {
        "a": rng.integers(0, 200, n),
        "b": rng.integers(0, 200, n),
        "v": rng.random(n).astype(np.float32),
    }
    ds = build_datasource(
        "bt", cols, dimension_cols=["a", "b"], metric_cols=["v"],
        rows_per_segment=n // 2,
        dicts={
            "a": DimensionDict(values=tuple(range(200))),
            "b": DimensionDict(values=tuple(range(200))),
        },
    )
    aggs = (Count("n"), DoubleSum("s", "v"))
    queries = [
        GroupByQuery(datasource="bt", dimensions=(DimensionSpec("a"),),
                     aggregations=aggs),
        GroupByQuery(datasource="bt",
                     dimensions=(DimensionSpec("a"), DimensionSpec("b")),
                     aggregations=aggs,
                     filter=InFilter("a", tuple(range(40)))),
        GroupByQuery(datasource="bt", dimensions=(), aggregations=aggs),
    ]
    serial_eng = Engine()
    want = [serial_eng.execute(q, ds) for q in queries]
    batch_eng = Engine()
    got = batch_eng.execute_groupby_batch(queries, ds)
    import pandas as pd

    for w, g in zip(want, got):
        pd.testing.assert_frame_equal(
            w.reset_index(drop=True), g.reset_index(drop=True)
        )


def test_code_dtype_boundaries():
    """Narrow-code width selection holds codes [-1, card) exactly at the
    signed-dtype boundaries: max stored code is card-1, so card=128 still
    fits int8 and card=32768 still fits int16."""
    from spark_druid_olap_tpu.catalog.segment import code_dtype

    assert code_dtype(1) == np.int8
    assert code_dtype(128) == np.int8
    assert code_dtype(129) == np.int16
    assert code_dtype(32768) == np.int16
    assert code_dtype(32769) == np.int32
    assert code_dtype(5_000_000) == np.int32
    for card in (128, 129, 32768, 32769):
        dt = code_dtype(card)
        assert np.array(-1, dt) == -1
        assert int(np.array(card - 1, dt)) == card - 1
