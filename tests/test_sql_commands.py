"""Non-SELECT SQL commands (reference parser-extension analogs)."""

import numpy as np
import pytest

import spark_druid_olap_tpu as sd


@pytest.fixture()
def ctx():
    c = sd.TPUOlapContext()
    rng = np.random.default_rng(0)
    for name in ("a", "b"):
        c.register_table(
            name,
            {
                "d": rng.integers(0, 4, 1000).astype(np.int64),
                "v": rng.random(1000).astype(np.float32),
            },
            dimensions=["d"],
            metrics=["v"],
        )
    return c


def test_show_tables(ctx):
    out = ctx.sql("SHOW TABLES")
    assert list(out["table"]) == ["a", "b"]


def test_drop_table(ctx):
    ctx.sql("DROP TABLE a")
    assert ctx.catalog.get("a") is None
    with pytest.raises(KeyError):
        ctx.sql("DROP TABLE a")
    ctx.sql("DROP TABLE IF EXISTS a")  # no raise


def test_clear_cache(ctx):
    ctx.sql("SELECT d, sum(v) AS s FROM a GROUP BY d")
    assert ctx.engine.bytes_resident() > 0
    out = ctx.sql("CLEAR CACHE")
    assert out["status"][0] == "cache cleared"
    assert ctx.engine.bytes_resident() == 0
    assert ctx.catalog.tables() == []


def test_select_still_works_after_command_dispatch(ctx):
    out = ctx.sql("SELECT count(*) AS n FROM b")
    assert int(out["n"][0]) == 1000
