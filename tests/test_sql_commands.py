"""Non-SELECT SQL commands (reference parser-extension analogs)."""

import numpy as np
import pytest

import spark_druid_olap_tpu as sd


@pytest.fixture()
def ctx():
    c = sd.TPUOlapContext()
    rng = np.random.default_rng(0)
    for name in ("a", "b"):
        c.register_table(
            name,
            {
                "d": rng.integers(0, 4, 1000).astype(np.int64),
                "v": rng.random(1000).astype(np.float32),
            },
            dimensions=["d"],
            metrics=["v"],
        )
    return c


def test_show_tables(ctx):
    out = ctx.sql("SHOW TABLES")
    assert list(out["table"]) == ["a", "b"]


def test_drop_table(ctx):
    ctx.sql("DROP TABLE a")
    assert ctx.catalog.get("a") is None
    with pytest.raises(KeyError):
        ctx.sql("DROP TABLE a")
    ctx.sql("DROP TABLE IF EXISTS a")  # no raise


def test_clear_cache(ctx):
    ctx.sql("SELECT d, sum(v) AS s FROM a GROUP BY d")
    assert ctx.engine.bytes_resident() > 0
    out = ctx.sql("CLEAR CACHE")
    assert out["status"][0] == "cache cleared"
    assert ctx.engine.bytes_resident() == 0
    assert ctx.catalog.tables() == []


def test_select_still_works_after_command_dispatch(ctx):
    out = ctx.sql("SELECT count(*) AS n FROM b")
    assert int(out["n"][0]) == 1000


def test_describe(ctx):
    df = ctx.sql("DESCRIBE a")
    assert list(df["column"]) == ["d", "v"]
    assert list(df["kind"]) == ["dimension", "metric"]
    df2 = ctx.sql("SHOW COLUMNS FROM a")
    assert list(df2["column"]) == list(df["column"])
    with pytest.raises(KeyError):
        ctx.sql("DESCRIBE nope")


def test_set_flag(ctx):
    out = ctx.sql("SET count_distinct_mode = 'exact'")
    assert "exact" in out["status"][0]
    assert ctx.config.count_distinct_mode == "exact"
    ctx.sql("SET prefer_distributed = false")
    assert ctx.config.prefer_distributed is False
    ctx.sql("SET hll_precision = 12")
    assert ctx.config.hll_precision == 12
    with pytest.raises(KeyError):
        ctx.sql("SET not_a_flag = 1")
    # bare SET lists every flag
    allf = ctx.sql("SET")
    assert "count_distinct_mode" in list(allf["key"])


def test_set_invalidates_plan_cache(ctx):
    """Flipping a flag must change planning for already-seen SQL."""
    sql = "SELECT count(DISTINCT d) AS n FROM a"
    ctx.sql("SET count_distinct_mode = 'approx'")
    ctx.sql(sql)  # populate plan cache under approx
    ctx.sql("SET count_distinct_mode = 'error'")
    with pytest.raises(Exception):
        ctx.sql(sql)


def test_create_table_using_options(ctx, tmp_path):
    import pandas as pd

    p = tmp_path / "t.csv"
    pd.DataFrame(
        {
            "city": ["NY", "SF", "NY", "LA"],
            "ts": pd.to_datetime(
                ["2021-01-01", "2021-01-02", "2021-01-03", "2021-01-04"]
            ),
            "v": [1.0, 2.0, 3.0, 4.0],
        }
    ).to_csv(p, index=False)
    out = ctx.sql(
        f"CREATE TABLE ev USING csv OPTIONS (path '{p}', timeColumn 'ts', "
        "dimensions 'city', metrics 'v', rowsPerSegment '1024')"
    )
    assert "created ev" in out["status"][0]
    df = ctx.sql("SELECT city, sum(v) AS s FROM ev GROUP BY city ORDER BY city")
    assert list(df["city"]) == ["LA", "NY", "SF"]
    assert list(df["s"]) == [4.0, 4.0, 2.0]
    with pytest.raises(ValueError):
        ctx.sql("CREATE TABLE x USING csv OPTIONS (nope 'y')")


def test_result_cache_hits_and_invalidates(ctx):
    sql = "SELECT d, sum(v) AS s FROM a GROUP BY d ORDER BY d"
    r1 = ctx.sql(sql)
    r2 = ctx.sql(sql)  # served from the result cache
    assert r1.equals(r2)
    # mutating the returned frame must not poison the cache (copies)
    r2["s"] = 0.0
    r3 = ctx.sql(sql)
    assert r3.equals(r1)
    # re-registration (new schema signature) invalidates
    rng = np.random.default_rng(1)
    ctx.register_table(
        "a",
        {
            "d": rng.integers(0, 4, 500).astype(np.int64),
            "v": np.ones(500, np.float32),
        },
        dimensions=["d"],
        metrics=["v"],
    )
    r4 = ctx.sql(sql)
    assert float(r4["s"].sum()) == 500.0


def test_set_optional_int_coerces(ctx):
    ctx.sql("SET mesh_data_axis = 4")
    assert ctx.config.mesh_data_axis == 4  # int, not the string '4'
    ctx.sql("SET mesh_data_axis = none")
    assert ctx.config.mesh_data_axis is None


def test_create_table_rejects_malformed_options(ctx):
    with pytest.raises(ValueError, match="malformed OPTIONS"):
        ctx.sql("CREATE TABLE x USING csv OPTIONS (path '/a.csv', rowsPerSegment 1024)")
    with pytest.raises(ValueError, match="supported providers"):
        ctx.sql("CREATE TABLE x USING orc OPTIONS (path '/a.orc')")
    with pytest.raises(ValueError, match="different\\s+extension"):
        ctx.sql("CREATE TABLE x USING parquet OPTIONS (path '/a.csv')")


def test_explain_analyze_bypasses_result_cache(ctx):
    sql = "SELECT d, count(*) AS n FROM b GROUP BY d"
    ctx.sql(sql)  # populate result cache
    df, report = ctx.explain_analyze(sql)
    assert "Execution Metrics" in report
    m = ctx.last_metrics
    assert m is not None and m.query_type == "groupBy"


def test_set_none_only_for_optional(ctx):
    with pytest.raises(ValueError, match="does not accept none"):
        ctx.sql("SET result_cache_entries = none")
    assert isinstance(ctx.config.result_cache_entries, int)


def test_set_result_cache_zero_releases_entries(ctx):
    ctx.sql("SET result_cache_entries = 64")
    ctx.sql("SELECT d, sum(v) AS s FROM a GROUP BY d")
    assert len(ctx._result_cache) >= 1
    ctx.sql("SET result_cache_entries = 0")
    assert len(ctx._result_cache) == 0


# -- round-3: CREATE VIEW / DROP VIEW / CREATE TABLE AS SELECT -------------


def _view_ctx():
    import spark_druid_olap_tpu as sd

    c = sd.TPUOlapContext()
    c.register_table(
        "vt",
        {
            "g": np.array(["a", "a", "b", "c"], dtype=object),
            "v": np.array([1.0, 2.0, 3.0, 4.0], np.float32),
        },
        dimensions=["g"],
        metrics=["v"],
    )
    return c


def test_create_view_and_query():
    c = _view_ctx()
    c.sql("CREATE VIEW big AS SELECT g, sum(v) AS s FROM vt GROUP BY g")
    got = c.sql("SELECT count(*) AS n FROM big WHERE s > 3")
    assert int(got["n"].iloc[0]) == 1  # sums a=3, b=3, c=4 -> only c
    # aggregate OVER the view (nested aggregation through a derived table)
    got2 = c.sql("SELECT max(s) AS m FROM big")
    assert float(got2["m"].iloc[0]) == 4.0
    tables = c.sql("SHOW TABLES")
    assert ("big", "view") in list(zip(tables["table"], tables["kind"]))


def test_view_over_view_and_redefinition_invalidates():
    c = _view_ctx()
    c.sql("CREATE VIEW v1 AS SELECT g, sum(v) AS s FROM vt GROUP BY g")
    c.sql("CREATE VIEW v2 AS SELECT s FROM v1 WHERE s >= 3")
    assert len(c.sql("SELECT s FROM v2")) == 3
    # OR REPLACE changes v1; v2 must see the new definition (plan cache
    # keys on the view registry)
    c.sql(
        "CREATE OR REPLACE VIEW v1 AS "
        "SELECT g, sum(v) AS s FROM vt WHERE g <> 'c' GROUP BY g"
    )
    assert len(c.sql("SELECT s FROM v2")) == 2  # a(3), b(3) remain >= 3


def test_view_validation_and_drop():
    import pytest as _pytest

    c = _view_ctx()
    with _pytest.raises(Exception):
        # definition must PARSE at CREATE time (syntax error surfaces now)
        c.sql("CREATE VIEW bad AS SELECT FROM vt WHERE")
    c.sql("CREATE VIEW ok AS SELECT g FROM vt")
    c.sql("DROP VIEW ok")
    with _pytest.raises(Exception):
        c.sql("SELECT * FROM ok")
    c.sql("DROP VIEW IF EXISTS ok")  # no error
    with _pytest.raises(KeyError):
        c.sql("DROP VIEW ok")


def test_ctas_materializes():
    c = _view_ctx()
    c.sql(
        "CREATE TABLE rollup1 AS "
        "SELECT g, sum(v) AS s, count(*) AS n FROM vt GROUP BY g"
    )
    ds = c.catalog.get("rollup1")
    assert ds is not None and ds.num_rows == 3
    got = c.sql("SELECT g, s FROM rollup1 ORDER BY s DESC LIMIT 1")
    assert got["g"].iloc[0] == "c" and float(got["s"].iloc[0]) == 4.0
    import pytest as _pytest

    with _pytest.raises(ValueError, match="already exists"):
        c.sql("CREATE TABLE rollup1 AS SELECT g FROM vt")


def test_setop_view():
    """Views defined as set operations expand through the union fold."""
    c = _view_ctx()
    c.register_table(
        "vt2",
        {"g": np.array(["b", "d"], dtype=object),
         "v": np.array([9.0, 9.0], np.float32)},
        dimensions=["g"], metrics=["v"],
    )
    c.sql("CREATE VIEW allg AS SELECT g FROM vt UNION SELECT g FROM vt2")
    got = c.sql("SELECT g, count(*) AS n FROM allg GROUP BY g ORDER BY g")
    assert list(got["g"]) == ["a", "b", "c", "d"]
    assert (got["n"] == 1).all()
    got2 = c.sql(
        "CREATE TABLE mat AS SELECT g FROM vt EXCEPT SELECT g FROM vt2"
    )
    assert c.catalog.get("mat").num_rows == 2  # a, c


def test_view_table_name_collisions_rejected():
    import pytest as _pytest

    c = _view_ctx()
    with _pytest.raises(ValueError, match="shadow"):
        c.sql("CREATE VIEW vt AS SELECT g FROM vt")  # table vt exists
    c.sql("CREATE VIEW okv AS SELECT g FROM vt")
    with _pytest.raises(ValueError, match="shadow"):
        c.sql("CREATE TABLE okv AS SELECT g FROM vt")  # view okv exists


def test_describe_view_shows_definition():
    c = _view_ctx()
    c.sql("CREATE VIEW dv AS SELECT g FROM vt")
    out = c.sql("DESCRIBE dv")
    assert out["view"].iloc[0] == "dv"
    assert "SELECT g FROM vt" in out["definition"].iloc[0]
