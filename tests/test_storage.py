"""Durable storage tier (ISSUE 13): append WAL, crash-safe persistent
segments, kill-and-restart recovery.

The contract under test, everywhere: a "kill" is a fresh
`TPUOlapContext(SessionConfig(storage_dir=d))` over the same directory
with NO shutdown of the old context — exactly what a SIGKILL leaves
behind.  After any kill at any armed fault site, the restarted node
must serve answers equal to a from-scratch oracle over the rows whose
appends were ACKED (un-acked batches may surface fully or not at all,
never partially), with zero re-ingest: historical segments come back
memmap-backed off the snapshot, and only the WAL tail replays.
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.catalog.persist import (
    LazyColumnMap,
    SNAPSHOT_NAME,
)
from spark_druid_olap_tpu.ingest.wal import (
    MAGIC,
    WriteAheadLog,
    decode_batch,
    encode_batch,
)
from spark_druid_olap_tpu.resilience import InjectedFault, injector

T0 = int(np.datetime64("2023-01-01", "ms").astype(np.int64))
DAY = 86_400_000

Q = (
    "SELECT city, sum(qty) AS q, count(*) AS n "
    "FROM ev GROUP BY city ORDER BY city"
)


@pytest.fixture(autouse=True)
def _disarm():
    injector().disarm()
    yield
    injector().disarm()


def _base_cols(n=120, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(
            np.array(["austin", "boston", "chicago"], dtype=object), n
        ),
        "qty": rng.integers(1, 100, n).astype(np.int64),
        "ts": T0 + rng.integers(0, 30, n) * DAY,
    }


def _append_cols(n=40, seed=7):
    return _base_cols(n, seed)


def _ctx(d, **kw):
    return sd.TPUOlapContext(sd.SessionConfig(storage_dir=str(d), **kw))


def _register(ctx, cols=None, **kw):
    return ctx.register_table(
        "ev", cols if cols is not None else _base_cols(),
        dimensions=["city"], metrics=["qty"], time_column="ts", **kw
    )


def _oracle(*col_maps):
    """Query result for the concatenation of `col_maps`, re-ingested
    from scratch in a non-durable context."""
    cat = {
        k: np.concatenate([np.asarray(c[k]) for c in col_maps])
        for k in col_maps[0]
    }
    ctx = sd.TPUOlapContext()
    ctx.register_table(
        "ev", cat, dimensions=["city"], metrics=["qty"], time_column="ts"
    )
    return ctx.sql(Q)


# -- WAL unit level ----------------------------------------------------------


def test_encode_decode_roundtrip():
    cols = {
        "city": np.asarray(["a", None, "c"], dtype=object),
        "qty": np.asarray([1, 2, 3], dtype=np.int64),
        "rev": np.asarray([0.5, 1.5, 2.5], dtype=np.float32),
    }
    ds, out, n = decode_batch(encode_batch("ev", cols, 3))
    assert ds == "ev" and n == 3
    assert list(out["city"]) == ["a", None, "c"]
    assert out["qty"].dtype == np.int64
    assert np.array_equal(out["qty"], cols["qty"])
    assert out["rev"].dtype == np.float32
    assert np.array_equal(out["rev"], cols["rev"])


def test_wal_seq_monotone_and_reopen_seeds(tmp_path):
    p = str(tmp_path / "wal.log")
    w = WriteAheadLog(p)
    cols = {"x": np.arange(4, dtype=np.int64)}
    assert w.last_seq == -1
    assert [w.append("ev", cols, 4) for _ in range(3)] == [0, 1, 2]
    assert w.last_seq == 2
    w.close()
    # a restarted process must never reuse a seq
    w2 = WriteAheadLog(p)
    assert w2.last_seq == 2
    assert w2.append("ev", cols, 4) == 3
    w2.close()


def test_wal_truncate_through_keeps_tail(tmp_path):
    p = str(tmp_path / "wal.log")
    w = WriteAheadLog(p)
    for i in range(5):
        w.append("ev", {"x": np.asarray([i], dtype=np.int64)}, 1)
    assert w.truncate_through(2) == 2
    got = list(w.scan())
    assert [seq for seq, _, _, _ in got] == [3, 4]
    assert [int(c["x"][0]) for _, _, c, _ in got] == [3, 4]
    w.close()
    assert WriteAheadLog(p).last_seq == 4


def test_wal_torn_tail_every_byte_boundary(tmp_path):
    """ISSUE 13 satellite 4: truncate the log at EVERY byte boundary of
    the final record.  Replay must return the two whole records intact
    and drop the torn third cleanly — full restore or full drop of the
    tail, never a partial batch."""
    p = str(tmp_path / "wal.log")
    w = WriteAheadLog(p)
    batches = [
        {"city": np.asarray(["a", "b"], dtype=object),
         "qty": np.asarray([i, i + 1], dtype=np.int64)}
        for i in range(3)
    ]
    for b in batches:
        w.append("ev", b, 2)
    w.close()
    blob = open(p, "rb").read()
    # offset where record 2 (the final one) begins
    w2 = WriteAheadLog(p)
    sizes = []
    off = 0
    import struct
    import zlib
    head = struct.Struct("<4sIQI")
    for _ in range(3):
        _, plen, _, _ = head.unpack_from(blob, off)
        sizes.append(head.size + plen)
        off += head.size + plen
    assert off == len(blob)
    w2.close()
    tail_start = sizes[0] + sizes[1]

    torn = str(tmp_path / "torn.log")
    for cut in range(tail_start, len(blob)):
        with open(torn, "wb") as fh:
            fh.write(blob[:cut])
        got = list(WriteAheadLog(torn).scan())
        assert len(got) == 2, f"cut at byte {cut}: {len(got)} records"
        for i, (seq, ds, cols, n) in enumerate(got):
            assert seq == i and ds == "ev" and n == 2
            assert np.array_equal(cols["qty"], batches[i]["qty"])
    # the untruncated log replays all three
    assert len(list(WriteAheadLog(p).scan())) == 3


def test_wal_corrupt_record_stops_scan(tmp_path):
    p = str(tmp_path / "wal.log")
    w = WriteAheadLog(p)
    for i in range(2):
        w.append("ev", {"x": np.asarray([i], dtype=np.int64)}, 1)
    w.close()
    blob = bytearray(open(p, "rb").read())
    blob[len(blob) // 2] ^= 0xFF  # flip a byte mid-log
    with open(p, "wb") as fh:
        fh.write(bytes(blob))
    got = list(WriteAheadLog(p).scan())
    # everything from the corrupt record onward is dropped whole
    assert all(np.array_equal(c["x"], [s]) for s, _, c, _ in got)
    assert len(got) < 2


def test_wal_bad_magic_stops_scan(tmp_path):
    p = str(tmp_path / "wal.log")
    w = WriteAheadLog(p)
    w.append("ev", {"x": np.asarray([1], dtype=np.int64)}, 1)
    w.close()
    with open(p, "ab") as fh:
        fh.write(b"XXXX" + b"\x00" * 20)
    assert len(list(WriteAheadLog(p).scan())) == 1
    assert MAGIC == b"SDW1"


# -- kill-free restart (the post-ack crash) ----------------------------------


def test_restart_serves_identical_and_disk_backed(tmp_path):
    """Acked appends survive a kill: fresh context over the same dir,
    byte-identical answers, snapshot restored as memmaps (zero
    re-ingest), and only the WAL tail replayed."""
    base, extra = _base_cols(), _append_cols()
    ctx = _ctx(tmp_path)
    _register(ctx, base)
    ack = ctx.append_rows("ev", extra)
    assert ack["appended"] == 40
    want = ctx.sql(Q)

    ctx2 = _ctx(tmp_path)
    assert ctx2.sql(Q).equals(want)
    assert ctx2.sql(Q).equals(_oracle(base, extra))
    ds = ctx2.catalog.get("ev")
    assert all(
        isinstance(s.dims, LazyColumnMap) for s in ds.historical_segments()
    ), "snapshot restore must be mmap-backed, not re-encoded"
    rec = ctx2.storage.last_recovery
    assert rec["replayed_rows"] == 40 and rec["datasources"] == 1


def test_compaction_flushes_and_truncates_wal(tmp_path):
    base, extra = _base_cols(), _append_cols()
    ctx = _ctx(tmp_path)
    _register(ctx, base)
    ctx.append_rows("ev", extra)
    ctx.compact("ev")
    want = ctx.sql(Q)
    # the flush folded the WAL into the snapshot: nothing to replay
    ctx2 = _ctx(tmp_path)
    assert ctx2.storage.last_recovery["replayed_rows"] == 0
    assert ctx2.sql(Q).equals(want)
    assert ctx2.sql(Q).equals(_oracle(base, extra))


def test_version_monotone_across_restart(tmp_path):
    ctx = _ctx(tmp_path)
    _register(ctx)
    v1 = ctx.append_rows("ev", _append_cols())["datasourceVersion"]
    ctx2 = _ctx(tmp_path)
    v2 = ctx2.append_rows("ev", _append_cols(seed=9))["datasourceVersion"]
    assert v2 > v1, "restart must not regress the version stamp"


# -- kill-and-restart at every injected site ---------------------------------


@pytest.mark.parametrize(
    "site",
    ["wal.journal_write", "wal.pre_fsync", "wal.post_fsync_pre_publish"],
)
def test_kill_mid_append(tmp_path, site):
    """Un-acked appends surface fully or not at all, never partially;
    before the first journal byte they must be absent."""
    base, extra = _base_cols(), _append_cols()
    ctx = _ctx(tmp_path)
    _register(ctx, base)
    injector().arm(site, mode="error", times=1)
    with pytest.raises(InjectedFault):
        ctx.append_rows("ev", extra)
    injector().disarm()

    got = _ctx(tmp_path).sql(Q)
    without, with_ = _oracle(base), _oracle(base, extra)
    if site == "wal.journal_write":
        assert got.equals(without), "no journal byte landed: batch absent"
    else:
        # whole-or-absent: the record was mid-journal when the process
        # died — either truncation drops it whole or replay applies it
        # whole; any other answer is a partial batch
        assert got.equals(without) or got.equals(with_)


@pytest.mark.parametrize("site", ["persist.snapshot_rename", "compact.retire"])
def test_kill_mid_compaction(tmp_path, site):
    """Every acked row survives a kill at either side of the snapshot
    commit point, exactly."""
    base, extra = _base_cols(), _append_cols()
    ctx = _ctx(tmp_path)
    _register(ctx, base)
    ctx.append_rows("ev", extra)
    want = _oracle(base, extra)
    assert ctx.sql(Q).equals(want)

    injector().arm(site, mode="error", times=1)
    with pytest.raises(InjectedFault):
        ctx.compact("ev")
    injector().disarm()

    ctx2 = _ctx(tmp_path)
    assert ctx2.sql(Q).equals(want)
    # and the node is fully live again: append + compact + restart
    more = _append_cols(seed=11)
    ctx2.append_rows("ev", more)
    ctx2.compact("ev")
    assert _ctx(tmp_path).sql(Q).equals(_oracle(base, extra, more))


def test_retired_files_deleted_only_after_rename(tmp_path):
    """ISSUE 13 satellite 6 regression: a crash between writing the new
    snapshot and its rename must leave every file the OLD snapshot
    references on disk — retirement strictly follows the commit."""
    ctx = _ctx(tmp_path)
    _register(ctx)
    ctx.append_rows("ev", _append_cols())
    want = ctx.sql(Q)
    d = ctx.storage.dir_for("ev")
    old_snapshot = open(os.path.join(d, SNAPSHOT_NAME), "rb").read()
    old_refs = {f for f in os.listdir(d) if f.endswith(".npy")}
    assert old_refs, "registration flush should have persisted columns"

    injector().arm("persist.snapshot_rename", mode="error", times=1)
    with pytest.raises(InjectedFault):
        ctx.compact("ev")
    injector().disarm()

    # commit point untouched, every old column file still present
    assert open(os.path.join(d, SNAPSHOT_NAME), "rb").read() == old_snapshot
    assert old_refs <= set(os.listdir(d))
    assert _ctx(tmp_path).sql(Q).equals(want)


def test_kill_mid_replay_then_clean_restart(tmp_path):
    """A crash DURING boot replay is just another kill: the next boot
    starts from the unchanged snapshot + full WAL tail and recovers
    everything (the crashed boot published only to memory)."""
    base, extra = _base_cols(), _append_cols()
    ctx = _ctx(tmp_path)
    _register(ctx, base)
    ctx.append_rows("ev", extra)
    want = _oracle(base, extra)

    injector().arm("storage.replay_batch", mode="error", times=1)
    with pytest.raises(InjectedFault):
        _ctx(tmp_path)
    injector().disarm()
    assert _ctx(tmp_path).sql(Q).equals(want)


def test_kill_during_wal_replay_record_site(tmp_path):
    ctx = _ctx(tmp_path)
    _register(ctx)
    ctx.append_rows("ev", _append_cols())
    want = ctx.sql(Q)
    injector().arm("wal.replay_record", mode="error", times=1)
    with pytest.raises(InjectedFault):
        _ctx(tmp_path)
    injector().disarm()
    assert _ctx(tmp_path).sql(Q).equals(want)


# -- ingest-time rollup ------------------------------------------------------


def test_rollup_preaggregates_under_granularity(tmp_path):
    ctx = _ctx(tmp_path)
    _register(ctx, rollup_granularity="day")
    rows = {
        "city": np.asarray(["austin"] * 4 + ["boston"] * 2, dtype=object),
        "qty": np.asarray([1, 2, 3, 4, 10, 20], dtype=np.int64),
        "ts": np.asarray(
            [T0, T0 + 1, T0 + 2, T0 + DAY, T0, T0 + 3], dtype=np.int64
        ),
    }
    base_total = int(ctx.sql("SELECT count(*) AS n FROM ev")["n"][0])
    ack = ctx.append_rows("ev", rows)
    # austin day0 (3 rows) + austin day1 + boston day0 -> 3 rolled rows
    assert ack["appended"] == 6
    assert ack["totalRows"] == base_total + 3
    s = ctx.sql(
        "SELECT city, sum(qty) AS q FROM ev GROUP BY city ORDER BY city"
    )
    base = _oracle(_base_cols())
    base_q = {c: int(q) for c, q in zip(base["city"], base["q"])}
    got = {c: int(q) for c, q in zip(s["city"], s["q"])}
    assert got["austin"] == base_q["austin"] + 10
    assert got["boston"] == base_q["boston"] + 30
    # rolled rows are what the WAL journals: a restart replays them and
    # answers identically
    want = ctx.sql(Q)
    assert _ctx(tmp_path).sql(Q).equals(want)


def test_rollup_rejects_calendar_granularity(tmp_path):
    ctx = _ctx(tmp_path)
    with pytest.raises(ValueError):
        _register(ctx, rollup_granularity="month")


def test_rollup_requires_time_column():
    ctx = sd.TPUOlapContext()
    with pytest.raises(ValueError):
        ctx.register_table(
            "flat", {"city": np.asarray(["a"], dtype=object),
                     "qty": np.asarray([1], dtype=np.int64)},
            dimensions=["city"], metrics=["qty"],
            rollup_granularity="hour",
        )


# -- health / serving surface ------------------------------------------------


def test_health_storage_state_shape(tmp_path):
    ctx = _ctx(tmp_path)
    _register(ctx)
    ctx.append_rows("ev", _append_cols())
    st = ctx.storage.state()
    assert st["enabled"] is True
    assert st["root"] == str(tmp_path)
    assert st["replay_in_progress"] is False
    ev = st["datasources"]["ev"]
    assert ev["wal_last_seq"] >= 0
    assert ev["snapshot_version"] >= 1
    assert ev["dirty_delta_segments"] >= 1
    assert ev["dirty_delta_rows"] == 40
    # after a restart the recovery ledger is populated
    st2 = _ctx(tmp_path).storage.state()
    assert st2["last_recovery"]["replayed_rows"] == 40


def test_server_health_and_503_during_replay(tmp_path):
    from spark_druid_olap_tpu.server import OlapServer

    ctx = _ctx(tmp_path)
    _register(ctx)
    srv = OlapServer(ctx, port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/status/health", timeout=30
        ) as r:
            doc = json.loads(r.read())
        assert doc["storage"]["enabled"] is True
        assert "ev" in doc["storage"]["datasources"]

        payload = json.dumps(
            {"query": "SELECT count(*) AS n FROM ev"}
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/druid/v2/sql", data=payload,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        # a recovering node 503s queries with Retry-After
        ctx.storage.replay_in_progress = True
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 503
            assert ei.value.headers.get("Retry-After") is not None
            body = json.loads(ei.value.read())
            assert body["errorClass"] == "QueryUnavailableException"
        finally:
            ctx.storage.replay_in_progress = False
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
    finally:
        srv.shutdown()


def test_non_durable_context_has_no_storage():
    ctx = sd.TPUOlapContext()
    assert ctx.storage is None


# -- background snapshot-flush sweep (ISSUE 14 satellite) ---------------------


def test_sweep_once_flushes_dirty_deltas(tmp_path):
    from spark_druid_olap_tpu.obs import get_registry

    ctx = _ctx(tmp_path)
    _register(ctx)
    # registration flushed; a clean table is not re-flushed
    assert ctx.storage._dirty("ev") is False
    assert ctx.storage.sweep_once() == {"flushed": []}

    ctx.append_rows("ev", _append_cols())
    assert ctx.storage._dirty("ev") is True
    sweeps0 = get_registry().counter("sdol_snapshot_sweeps_total").value
    assert ctx.storage.sweep_once() == {"flushed": ["ev"]}
    assert ctx.storage._dirty("ev") is False
    assert (
        get_registry().counter("sdol_snapshot_sweeps_total").value
        == sweeps0 + 1
    )
    assert (
        get_registry()
        .counter("sdol_snapshot_sweep_flushes_total")
        .value
        >= 1
    )
    assert (
        ctx.storage.state()["flush_sweep"]["sweeps_total"]
        == ctx.storage.sweeps_total
        >= 2
    )

    # the sweep's flush covered the deltas: a restart mmaps the
    # snapshot and replays NOTHING, yet serves base + appended rows
    ctx2 = _ctx(tmp_path)
    assert ctx2.storage.last_recovery["replayed_rows"] == 0
    assert ctx2.sql(Q).equals(_oracle(_base_cols(), _append_cols()))


def test_flush_sweep_timer_thread(tmp_path):
    import time

    ctx = _ctx(tmp_path, snapshot_flush_s=0.05)
    try:
        assert ctx.storage.state()["flush_sweep"]["running"] is True
        assert ctx.storage.state()["flush_sweep"]["interval_s"] == 0.05
        _register(ctx)
        ctx.append_rows("ev", _append_cols())
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if not ctx.storage._dirty("ev"):
                break
            time.sleep(0.02)
        assert ctx.storage._dirty("ev") is False
        assert ctx.storage.sweeps_total >= 1
    finally:
        ctx.storage.close()
    assert ctx.storage.state()["flush_sweep"]["running"] is False
    # close() is idempotent wrt the sweep; a fresh context over the same
    # dir with the timer off never starts the thread
    ctx.storage.stop_flush_sweep()
    ctx3 = _ctx(tmp_path)
    assert ctx3.storage.state()["flush_sweep"]["running"] is False


# -- raise (not kill) at every durability site: the LIVE process must ---------
#    stay whole-or-absent and leak nothing (the GL29xx runtime contract)


def _assert_no_leaked_slots(ctx):
    """Every admission/lane slot released: the exception path must not
    leave a slot held (the GL2901 leak shape), and the registry gauges
    — what an operator actually watches — must agree."""
    from spark_druid_olap_tpu.obs import get_registry

    res = ctx.resilience
    assert res.admission.in_use == 0
    assert res.ingest_admission.in_use == 0
    for lane, pool in res.lanes.items():
        assert pool.in_use == 0, f"lane {lane} leaked a slot"
    for line in get_registry().render_prometheus().splitlines():
        if line.startswith("sdol_admission_slots_in_use") or (
            line.startswith("sdol_lane_slots_in_use")
        ):
            assert float(line.rsplit(" ", 1)[1]) == 0.0, line


@pytest.mark.parametrize(
    "site",
    ["wal.journal_write", "wal.pre_fsync", "wal.post_fsync_pre_publish"],
)
def test_raise_mid_append_whole_or_absent(tmp_path, site):
    """Unlike the kill matrix, the process SURVIVES the exception: the
    same live context must answer whole-or-absent (an un-acked batch is
    fully visible or fully absent, never torn), keep serving, and hold
    zero admission/lane slots afterwards."""
    base, extra = _base_cols(), _append_cols()
    ctx = _ctx(tmp_path)
    _register(ctx, base)
    injector().arm(site, mode="error", times=1)
    with pytest.raises(InjectedFault):
        ctx.append_rows("ev", extra)
    injector().disarm()

    without, with_ = _oracle(base), _oracle(base, extra)
    got = ctx.sql(Q)
    assert got.equals(without) or got.equals(with_), (
        "live context answered a TORN batch after an in-process raise"
    )
    # a restart must also be whole-or-absent — note it may legitimately
    # DISAGREE with the live answer at wal.post_fsync_pre_publish (the
    # batch is durable but unpublished: invisible live, replayed on
    # recovery); both states are within the un-acked contract
    got2 = _ctx(tmp_path).sql(Q)
    assert got2.equals(without) or got2.equals(with_)
    # the survivor is fully live: the next append lands whole
    more = _append_cols(seed=13)
    ctx.append_rows("ev", more)
    final = ctx.sql(Q)
    assert final.equals(_oracle(base, more)) or final.equals(
        _oracle(base, extra, more)
    )
    _assert_no_leaked_slots(ctx)


@pytest.mark.parametrize("site", ["persist.snapshot_rename", "compact.retire"])
def test_raise_mid_compaction_whole_or_absent(tmp_path, site):
    """An exception inside the snapshot-commit window loses NOTHING in
    the live process (every row was acked) and leaks no slot; the next
    compaction completes the interrupted flush."""
    base, extra = _base_cols(), _append_cols()
    ctx = _ctx(tmp_path)
    _register(ctx, base)
    ctx.append_rows("ev", extra)
    want = _oracle(base, extra)

    injector().arm(site, mode="error", times=1)
    with pytest.raises(InjectedFault):
        ctx.compact("ev")
    injector().disarm()

    assert ctx.sql(Q).equals(want), "live answer changed across a raise"
    assert _ctx(tmp_path).sql(Q).equals(want)
    # the survivor finishes the job: append + compact + restart agree
    more = _append_cols(seed=17)
    ctx.append_rows("ev", more)
    ctx.compact("ev")
    assert ctx.sql(Q).equals(_oracle(base, extra, more))
    assert _ctx(tmp_path).sql(Q).equals(_oracle(base, extra, more))
    _assert_no_leaked_slots(ctx)
