"""Real-time ingestion tier (ISSUE 6): parallel sharded bulk ingest,
append-only delta segments with query-time merge, versioned background
compaction.

The oracle contract under test everywhere: after any sequence of
appends/compactions, a query over the live datasource equals the same
query over a datasource re-ingested FROM SCRATCH with the full row set —
across groupBy / topN / timeseries and the host-fallback path.  Integer
metrics make the comparison exact (f32 sums are order-sensitive; int32
sums are not)."""

import dataclasses
import threading

import numpy as np
import pandas as pd
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.catalog.segment import (
    DeltaSegment,
    DimensionDict,
    build_datasource,
    extend_dict,
    remap_segment_codes,
)
from spark_druid_olap_tpu.ingest import (
    build_datasource_sharded,
    merge_shard_values,
)

T0 = int(np.datetime64("2022-01-01", "ms").astype(np.int64))
DAY = 86_400_000

CITIES = np.array(["austin", "boston", "chicago", "denver", "el paso"],
                  dtype=object)


def _rows(n, rng, cities=CITIES, year_lo=1995, year_hi=1999):
    return {
        "city": rng.choice(cities, n),
        "year": rng.integers(year_lo, year_hi, n).astype(np.int64),
        "qty": rng.integers(1, 100, n).astype(np.int64),
        "rev": (rng.random(n) * 100).astype(np.float32),
        "ts": T0 + rng.integers(0, 365, n) * DAY,
    }


def _register(ctx, name, cols, rows_per_segment=2048):
    return ctx.register_table(
        name, cols,
        dimensions=["city", "year"], metrics=["qty", "rev"],
        time_column="ts", rows_per_segment=rows_per_segment,
    )


def _concat(*col_maps):
    out = {}
    for k in col_maps[0]:
        out[k] = np.concatenate([np.asarray(c[k]) for c in col_maps])
    return out


QUERIES = {
    "groupby": "SELECT city, sum(qty) AS q, count(*) AS n FROM {t} "
               "GROUP BY city ORDER BY city",
    "groupby2": "SELECT city, year, sum(qty) AS q FROM {t} "
                "WHERE year >= 1996 GROUP BY city, year "
                "ORDER BY city, year",
    "topn": "SELECT city, sum(qty) AS q FROM {t} GROUP BY city "
            "ORDER BY q DESC LIMIT 3",
    "timeseries": "SELECT DATE_TRUNC('month', ts) AS m, sum(qty) AS q "
                  "FROM {t} GROUP BY DATE_TRUNC('month', ts) ORDER BY m",
}


def _assert_oracle_parity(ctx, name, full_cols, queries=QUERIES):
    """Live datasource == re-ingest-from-scratch oracle, per query."""
    oracle = sd.TPUOlapContext()
    _register(oracle, "oracle_t", full_cols)
    for label, sql in queries.items():
        got = ctx.sql(sql.format(t=name)).reset_index(drop=True)
        want = oracle.sql(sql.format(t="oracle_t")).reset_index(drop=True)
        want = want.rename(columns=dict(zip(want.columns, got.columns)))
        pd.testing.assert_frame_equal(got, want, check_dtype=False,
                                      obj=f"query {label}")


# ---------------------------------------------------------------------------
# sharded bulk ingest
# ---------------------------------------------------------------------------


def test_sharded_build_matches_serial_exactly():
    rng = np.random.default_rng(3)
    cols = _rows(10_000, rng)
    # sprinkle nulls into the string dim (object-column None handling)
    cols["city"][rng.integers(0, 10_000, 50)] = None
    serial = build_datasource(
        "t", cols, ["city", "year"], ["qty", "rev"], time_col="ts",
        rows_per_segment=2048,
    )
    sharded = build_datasource_sharded(
        "t", cols, ["city", "year"], ["qty", "rev"], time_col="ts",
        rows_per_segment=2048, workers=3,
    )
    assert sharded.dicts["city"].values == serial.dicts["city"].values
    assert sharded.dicts["year"].values == serial.dicts["year"].values
    assert len(sharded.segments) == len(serial.segments)
    for a, b in zip(serial.segments, sharded.segments):
        assert a.segment_id == b.segment_id
        assert a.num_rows == b.num_rows
        for c in ("city", "year"):
            np.testing.assert_array_equal(a.dims[c], b.dims[c])
        for c in ("qty", "rev"):
            np.testing.assert_array_equal(a.metrics[c], b.metrics[c])
        np.testing.assert_array_equal(a.time, b.time)
        np.testing.assert_array_equal(a.valid, b.valid)
        assert a.stats == b.stats
        assert a.interval == b.interval


def test_sharded_build_from_chunk_iterator_without_dicts():
    """The capability the serial streamed path lacks: a chunk STREAM with
    no pre-built dictionaries — phase 1 builds them with a deterministic
    merge, and queries agree with a from-scratch oracle."""
    rng = np.random.default_rng(4)
    chunks = [_rows(3000, rng) for _ in range(4)]
    # ragged chunk sizes exercise the resharder's buffering path
    chunks.append(_rows(777, rng))
    ds = build_datasource_sharded(
        "t", iter(chunks), ["city", "year"], ["qty", "rev"],
        time_col="ts", rows_per_segment=2048, workers=2,
    )
    full = _concat(*chunks)
    assert ds.num_rows == len(full["ts"])
    ctx = sd.TPUOlapContext()
    ctx.register_datasource(ds)
    _assert_oracle_parity(ctx, "t", full)


def test_merge_shard_values_deterministic_under_shard_order():
    a = np.array(["pear", "apple", None], dtype=object)
    b = np.array(["apple", "quince"], dtype=object)
    c = np.array([], dtype=object)
    d1 = merge_shard_values([a, b, c])
    d2 = merge_shard_values([c, b, a])
    assert d1.values == d2.values == ("apple", "pear", "quince")
    # numeric shards merge numerically sorted, negatives (nulls) excluded
    n1 = merge_shard_values([np.array([7, 3]), np.array([3, 11])])
    assert n1.values == (3, 7, 11)


# ---------------------------------------------------------------------------
# dictionary extension + code remap
# ---------------------------------------------------------------------------


def test_extend_dict_monotone_lut_and_remap():
    old = DimensionDict(values=("b", "d", "f"))
    new, lut = extend_dict(old, ["a", "d", "e"])
    assert new.values == ("a", "b", "d", "e", "f")
    # strictly monotone: code order keeps meaning value order
    np.testing.assert_array_equal(lut, [1, 2, 4])
    assert all(np.diff(lut) > 0)
    # nothing novel -> no LUT (the steady-state append)
    same, none_lut = extend_dict(new, ["a", "f"])
    assert none_lut is None and same is new


def test_remap_segment_codes_preserves_values_and_stats():
    rng = np.random.default_rng(5)
    cols = _rows(4000, rng)
    ds = build_datasource(
        "t", cols, ["city", "year"], ["qty", "rev"], time_col="ts",
        rows_per_segment=2048,
    )
    old_dict = ds.dicts["city"]
    new_dict, lut = extend_dict(old_dict, ["aachen", "miami"])
    seg = ds.segments[0]
    out = remap_segment_codes(
        seg, {"city": lut}, {"city": new_dict.cardinality}
    )
    # same decoded values under the new dictionary, fresh uid
    np.testing.assert_array_equal(
        new_dict.decode(np.asarray(out.dims["city"][: seg.num_rows])),
        old_dict.decode(np.asarray(seg.dims["city"][: seg.num_rows])),
    )
    assert out.uid != seg.uid
    # zone maps shifted through the same monotone LUT
    lo, hi = out.stats["city"]
    olo, ohi = seg.stats["city"]
    assert (lo, hi) == (float(lut[int(olo)]), float(lut[int(ohi)]))


# ---------------------------------------------------------------------------
# append-only delta segments: immediate visibility + oracle parity
# ---------------------------------------------------------------------------


def test_append_rows_visible_immediately_with_oracle_parity():
    rng = np.random.default_rng(6)
    base = _rows(9000, rng)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", base)

    batches = []
    # batch 1: wire-shaped row objects, known values
    b1 = [
        {"city": "austin", "year": 1997, "qty": 5, "rev": 1.5,
         "ts": T0 + 3 * DAY},
        {"city": "boston", "year": 1996, "qty": 7, "rev": 2.5,
         "ts": T0 + 100 * DAY},
    ]
    ack = ctx.append_rows("ev", b1)
    assert ack["appended"] == 2
    batches.append({
        "city": np.array(["austin", "boston"], dtype=object),
        "year": np.array([1997, 1996], dtype=np.int64),
        "qty": np.array([5, 7], dtype=np.int64),
        "rev": np.array([1.5, 2.5], dtype=np.float32),
        "ts": np.array([T0 + 3 * DAY, T0 + 100 * DAY], dtype=np.int64),
    })
    # batch 2: column-mapping shape, NOVEL string and numeric dim values
    b2 = {
        "city": np.array(["zanesville", "austin"], dtype=object),
        "year": np.array([2001, 1995], dtype=np.int64),
        "qty": np.array([11, 13], dtype=np.int64),
        "rev": np.array([3.5, 4.5], dtype=np.float32),
        "ts": np.array([T0 + 10 * DAY, T0 + 11 * DAY], dtype=np.int64),
    }
    v_before = ctx.catalog.datasource_version("ev")
    ack = ctx.append_rows("ev", b2)
    assert ack["appended"] == 2
    assert ack["datasourceVersion"] == v_before + 1
    batches.append(b2)
    # batch 3: rows with MISSING columns (null dim, zero metric)
    b3 = [{"city": "chicago", "year": 1998, "ts": T0 + 50 * DAY}]
    ctx.append_rows("ev", b3)
    batches.append({
        "city": np.array(["chicago"], dtype=object),
        "year": np.array([1998], dtype=np.int64),
        "qty": np.array([0], dtype=np.int64),
        "rev": np.array([0.0], dtype=np.float32),
        "ts": np.array([T0 + 50 * DAY], dtype=np.int64),
    })

    ds = ctx.catalog.get("ev")
    assert ds.delta_rows == 5
    assert len(ds.delta_segments()) == 3
    # novel values extended the (still sorted) dictionaries
    assert "zanesville" in ds.dicts["city"].values
    assert list(ds.dicts["city"].values) == sorted(ds.dicts["city"].values)
    assert 2001 in ds.dicts["year"].values

    full = _concat(base, *batches)
    _assert_oracle_parity(ctx, "ev", full)

    # filters that touch novel AND pre-existing values stay exact
    got = ctx.sql("SELECT sum(qty) AS q FROM ev WHERE city = 'zanesville'")
    assert int(got["q"][0]) == 11
    got = ctx.sql(
        "SELECT count(*) AS n FROM ev WHERE city = 'austin' AND year = 1997"
    )
    want = int(
        ((full["city"] == "austin") & (full["year"] == 1997)).sum()
    )
    assert int(got["n"][0]) == want


def test_append_parity_on_fallback_path():
    """Delta merge through the HOST interpreter: with rewrites disabled
    the fallback decodes the live segment set (historical + delta) and
    must agree with the from-scratch oracle."""
    rng = np.random.default_rng(7)
    base = _rows(5000, rng)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", base)
    extra = {
        "city": np.array(["waco", "austin"], dtype=object),
        "year": np.array([1999, 1996], dtype=np.int64),
        "qty": np.array([21, 22], dtype=np.int64),
        "rev": np.array([1.0, 2.0], dtype=np.float32),
        "ts": np.array([T0, T0 + DAY], dtype=np.int64),
    }
    ctx.append_rows("ev", extra)
    ctx.config.enable_rewrites = False
    got = ctx.sql(QUERIES["groupby"].format(t="ev"))
    assert ctx.last_metrics.executor == "fallback"
    oracle = sd.TPUOlapContext()
    _register(oracle, "o", _concat(base, extra))
    oracle.config.enable_rewrites = False
    want = oracle.sql(QUERIES["groupby"].format(t="o"))
    pd.testing.assert_frame_equal(
        got.reset_index(drop=True), want.reset_index(drop=True),
        check_dtype=False,
    )


def test_append_rejects_malformed_payloads():
    rng = np.random.default_rng(8)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", _rows(2000, rng))
    with pytest.raises(KeyError):
        ctx.append_rows("nope", [{"city": "x", "ts": T0}])
    with pytest.raises(ValueError, match="unknown columns"):
        ctx.append_rows("ev", [{"city": "x", "bogus": 1, "ts": T0}])
    with pytest.raises(ValueError, match="ragged"):
        ctx.append_rows("ev", {"city": ["a", "b"], "qty": [1],
                               "year": [1, 2], "rev": [0.5, 1.5],
                               "ts": [T0, T0]})
    with pytest.raises(ValueError, match="time column"):
        ctx.append_rows("ev", [{"city": "x", "year": 1995, "qty": 1}])
    # an empty append is an ack, not an error
    ack = ctx.append_rows("ev", [])
    assert ack["appended"] == 0


def test_append_invalidates_result_cache():
    rng = np.random.default_rng(9)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", _rows(4000, rng))
    q = "SELECT sum(qty) AS q FROM ev"
    first = int(ctx.sql(q)["q"][0])
    ctx.sql(q)
    assert ctx.last_metrics.strategy == "result-cache"  # warm
    ctx.append_rows("ev", [{"city": "austin", "year": 1997, "qty": 1000,
                            "rev": 0.0, "ts": T0}])
    got = ctx.sql(q)
    assert ctx.last_metrics.strategy != "result-cache"
    assert int(got["q"][0]) == first + 1000


# ---------------------------------------------------------------------------
# compaction: equivalence + versioned invalidation
# ---------------------------------------------------------------------------


def test_compaction_preserves_results_and_bumps_version():
    rng = np.random.default_rng(10)
    base = _rows(6000, rng)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", base)
    batches = [_rows(500, rng) for _ in range(4)]
    for b in batches:
        ctx.append_rows("ev", b)
    full = _concat(base, *batches)

    before = {
        k: ctx.sql(sql.format(t="ev")).reset_index(drop=True)
        for k, sql in QUERIES.items()
    }
    ds = ctx.catalog.get("ev")
    assert len(ds.delta_segments()) == 4
    v_before = ctx.catalog.datasource_version("ev")
    # prime the result cache so invalidation is observable
    q = "SELECT sum(qty) AS q FROM ev"
    ctx.sql(q)
    ctx.sql(q)
    assert ctx.last_metrics.strategy == "result-cache"

    summary = ctx.compact("ev")
    assert summary["compacted_rows"] == 2000
    assert summary["delta_segments"] == 4

    ds2 = ctx.catalog.get("ev")
    assert ds2.delta_segments() == ()
    assert ds2.num_rows == len(full["ts"])
    # monotonic version observed via catalog/cache.py
    assert ctx.catalog.datasource_version("ev") > v_before
    assert summary["datasourceVersion"] == ctx.catalog.datasource_version(
        "ev"
    )
    # the result cache did NOT serve the stale entry
    ctx.sql(q)
    assert ctx.last_metrics.strategy != "result-cache"

    after = {
        k: ctx.sql(sql.format(t="ev")).reset_index(drop=True)
        for k, sql in QUERIES.items()
    }
    for k in QUERIES:
        pd.testing.assert_frame_equal(before[k], after[k], obj=f"query {k}")
    _assert_oracle_parity(ctx, "ev", full)

    # compacting again is a no-op
    assert ctx.compact("ev")["compacted_rows"] == 0


def test_compaction_consolidates_tiny_deltas_and_evicts_residency():
    rng = np.random.default_rng(11)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", _rows(4000, rng))
    for _ in range(6):
        ctx.append_rows("ev", _rows(64, rng))
    ds = ctx.catalog.get("ev")
    assert len(ds.delta_segments()) == 6
    # make delta columns device-resident
    ctx.sql("SELECT city, sum(qty) AS q FROM ev GROUP BY city")
    delta_uids = {s.uid for s in ds.delta_segments()}
    assert any(k[0] in delta_uids for k in ctx.engine._device_cache)
    ctx.compact("ev")
    # residency of retired delta segments was evicted promptly
    assert not any(k[0] in delta_uids for k in ctx.engine._device_cache)
    ds2 = ctx.catalog.get("ev")
    assert len(ds2.segments) < len(ds.segments)


def test_background_compactor_sweeps():
    rng = np.random.default_rng(12)
    cfg = sd.SessionConfig.load_calibrated()
    cfg.compaction_interval_s = 0.05
    cfg.compaction_min_delta_rows = 1
    ctx = sd.TPUOlapContext(cfg)
    _register(ctx, "ev", _rows(3000, rng))
    ctx.append_rows("ev", _rows(128, rng))
    assert ctx.catalog.get("ev").delta_rows == 128
    ctx.start_compaction()
    try:
        deadline = threading.Event()
        for _ in range(100):
            if not ctx.catalog.get("ev").delta_segments():
                break
            deadline.wait(0.05)
        assert ctx.catalog.get("ev").delta_segments() == ()
    finally:
        ctx.stop_compaction()


# ---------------------------------------------------------------------------
# concurrency: append-while-query hammer
# ---------------------------------------------------------------------------


def test_concurrent_append_query_hammer():
    rng = np.random.default_rng(13)
    base = _rows(4000, rng)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", base)
    n_appenders, batches_per, batch_rows = 3, 8, 32
    errors = []
    counts = []

    def appender(seed):
        r = np.random.default_rng(seed)
        try:
            for _ in range(batches_per):
                ctx.append_rows("ev", _rows(batch_rows, r))
        except Exception as e:  # pragma: no cover - surfaced by assert
            errors.append(e)

    def querier():
        try:
            seen = 0
            for _ in range(12):
                got = ctx.sql("SELECT count(*) AS n FROM ev")
                n = int(got["n"][0])
                # visibility is monotone: a later query can never see
                # fewer rows than an earlier one
                assert n >= seen and n >= 4000
                seen = n
        except Exception as e:  # pragma: no cover
            errors.append(e)
        else:
            counts.append(seen)

    threads = [
        threading.Thread(target=appender, args=(100 + i,))
        for i in range(n_appenders)
    ] + [threading.Thread(target=querier) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    total = 4000 + n_appenders * batches_per * batch_rows
    got = ctx.sql("SELECT count(*) AS n FROM ev")
    assert int(got["n"][0]) == total
    # a compaction after the storm preserves the exact count
    ctx.compact("ev")
    got = ctx.sql("SELECT count(*) AS n FROM ev")
    assert int(got["n"][0]) == total


def test_append_honors_deadline_checkpoints():
    """A novel-value append remaps every segment; an expired deadline
    cancels between segments instead of finishing the whole remap."""
    from spark_druid_olap_tpu.resilience import (
        DeadlineExceeded,
        deadline_scope,
    )

    rng = np.random.default_rng(14)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", _rows(20_000, rng), rows_per_segment=1024)
    with pytest.raises(DeadlineExceeded):
        with deadline_scope(0.000001):
            ctx.append_rows("ev", [{"city": "novelville", "year": 1997,
                                    "qty": 1, "rev": 0.0, "ts": T0}])


# ---------------------------------------------------------------------------
# learned-memo stability across appends (exec-layer integration)
# ---------------------------------------------------------------------------


def test_memo_key_stable_across_appends():
    from spark_druid_olap_tpu.exec.lowering import memo_key
    from spark_druid_olap_tpu.models import query as Q
    from spark_druid_olap_tpu.models.aggregations import LongSum
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec

    rng = np.random.default_rng(15)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", _rows(3000, rng))
    q = Q.GroupByQuery(
        datasource="ev",
        dimensions=(DimensionSpec("city"),),
        aggregations=(LongSum("q", "qty"),),
    )
    ds1 = ctx.catalog.get("ev")
    k1 = memo_key(q, ds1)
    # same-domain append: memo identity stable (learned rungs survive)
    ctx.append_rows("ev", [{"city": "austin", "year": 1997, "qty": 1,
                            "rev": 0.0, "ts": T0}])
    ds2 = ctx.catalog.get("ev")
    assert memo_key(q, ds2) == k1
    # dictionary extension: memo identity changes (rungs re-learn)
    ctx.append_rows("ev", [{"city": "new city", "year": 1997, "qty": 1,
                            "rev": 0.0, "ts": T0}])
    ds3 = ctx.catalog.get("ev")
    assert memo_key(q, ds3) != k1


# ---------------------------------------------------------------------------
# label-cardinality guard (obs satellite (b))
# ---------------------------------------------------------------------------


def test_bounded_label_caps_hostile_name_stream():
    from spark_druid_olap_tpu.obs.registry import LABEL_OVERFLOW, bounded_label

    fam = "test_guard_family_unique"
    admitted = set()
    for i in range(200):
        admitted.add(bounded_label(fam, f"ds_{i}", cap=16))
    assert LABEL_OVERFLOW in admitted
    assert len(admitted) == 17  # 16 admitted + the overflow bucket
    # admitted names stay stable (series continuity)
    assert bounded_label(fam, "ds_3", cap=16) == "ds_3"
    assert bounded_label(fam, "ds_199", cap=16) == LABEL_OVERFLOW


def test_ingest_counters_guarded_per_datasource():
    from spark_druid_olap_tpu.obs import get_registry
    from spark_druid_olap_tpu.obs.registry import record_ingest

    for i in range(200):
        record_ingest(f"hostile_{i}", rows=1, outcome="ok")
    fam = get_registry().counter(
        "sdol_ingest_requests_total",
        "streamed ingest appends, by datasource / outcome",
        labels=("datasource", "outcome"),
    )
    # the registry family stays bounded: cap + overflow (the guard
    # family is process-global and shared with real ingests, so <=)
    assert len(fam.snapshot()) <= 65


def test_query_counter_carries_datasource_label():
    from spark_druid_olap_tpu.obs import get_registry

    rng = np.random.default_rng(18)
    ctx = sd.TPUOlapContext()
    _register(ctx, "labeled_ds", _rows(2000, rng))
    ctx.sql("SELECT city, sum(qty) AS q FROM labeled_ds GROUP BY city")
    fam = get_registry().counter(
        "sdol_datasource_queries_total",
        "queries executed, by datasource / wire type",
        labels=("datasource", "query_type"),
    )
    assert any("labeled_ds" in k for k in fam.snapshot())


# ---------------------------------------------------------------------------
# the HTTP ingest route
# ---------------------------------------------------------------------------


@pytest.fixture()
def served():
    from spark_druid_olap_tpu.server import OlapServer

    rng = np.random.default_rng(16)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", _rows(3000, rng))
    srv = OlapServer(ctx, port=0).start()
    yield ctx, srv
    srv.shutdown()


def _post(srv, path, payload, expect_error=False):
    import json
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        if not expect_error:
            raise
        return e.code, json.loads(e.read()), dict(e.headers)


def test_http_ingest_route_end_to_end(served):
    ctx, srv = served
    status, ack, headers = _post(
        srv, "/druid/v2/ingest/ev",
        {"rows": [
            {"city": "austin", "year": 1997, "qty": 40, "rev": 1.0,
             "ts": T0},
            {"city": "brand new", "year": 1995, "qty": 2, "rev": 2.0,
             "ts": T0 + DAY},
        ], "context": {"queryId": "ingest-42"}},
    )
    assert status == 200
    assert ack["appended"] == 2
    assert headers.get("X-Druid-Query-Id") == "ingest-42"
    # appended rows serve on the very next query — SQL route
    status, rows, _ = _post(
        srv, "/druid/v2/sql",
        {"query": "SELECT sum(qty) AS q FROM ev WHERE city = 'austin' "
                  "AND year = 1997"},
    )
    assert status == 200
    full_q = rows[0]["q"]
    assert full_q >= 40
    # ... and on the NATIVE route (wire queries share the live snapshot)
    status, res, _ = _post(
        srv, "/druid/v2",
        {"queryType": "groupBy", "dataSource": "ev",
         "dimensions": ["city"],
         "aggregations": [{"type": "longSum", "name": "q",
                           "fieldName": "qty"}],
         "granularity": "all"},
    )
    assert status == 200
    by_city = {r["event"]["city"]: r["event"]["q"] for r in res}
    assert by_city.get("brand new") == 2
    # columns-shape payload
    status, ack, _ = _post(
        srv, "/druid/v2/ingest/ev",
        {"columns": {"city": ["austin"], "year": [1998], "qty": [3],
                     "rev": [0.5], "ts": [T0 + 2 * DAY]}},
    )
    assert status == 200 and ack["appended"] == 1


def test_http_ingest_route_client_errors(served):
    ctx, srv = served
    status, err, _ = _post(
        srv, "/druid/v2/ingest/nope", {"rows": [{"city": "x", "ts": T0}]},
        expect_error=True,
    )
    assert status == 400 and "unknown dataSource" in err["error"]
    status, err, _ = _post(
        srv, "/druid/v2/ingest/ev", {"bogus": 1}, expect_error=True,
    )
    assert status == 400
    status, err, _ = _post(
        srv, "/druid/v2/ingest/ev",
        {"rows": [{"city": "x", "wat": 1, "ts": T0}]}, expect_error=True,
    )
    assert status == 400 and "unknown columns" in err["error"]


def test_http_ingest_admission_503(served):
    ctx, srv = served
    adm = ctx.resilience.ingest_admission
    adm.queue_timeout_ms = 50.0
    # exhaust every ingest slot, then a request must shed with 503
    held = 0
    while adm.acquire():
        held += 1
        if held >= adm.max_concurrent:
            break
    try:
        status, err, headers = _post(
            srv, "/druid/v2/ingest/ev",
            {"rows": [{"city": "austin", "year": 1997, "qty": 1,
                       "rev": 0.0, "ts": T0}]},
            expect_error=True,
        )
        assert status == 503
        assert "Retry-After" in headers
        assert err["errorClass"] == "QueryCapacityExceededException"
    finally:
        for _ in range(held):
            adm.release()


def test_health_exposes_ingest_admission(served):
    import json
    import urllib.request

    ctx, srv = served
    with urllib.request.urlopen(
        f"http://127.0.0.1:{srv.port}/status/health", timeout=30
    ) as r:
        health = json.loads(r.read())
    assert "ingest_admission" in health
    assert health["ingest_admission"]["slots_total"] == (
        ctx.config.max_concurrent_ingests
    )


# ---------------------------------------------------------------------------
# fallback decode cache stays delta-correct
# ---------------------------------------------------------------------------


def test_fallback_decode_cache_sees_appends():
    """The per-segment decode cache must never serve a pre-append frame:
    uid-keyed entries reuse historical decodes but fresh deltas decode."""
    rng = np.random.default_rng(17)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", _rows(3000, rng))
    ctx.config.enable_rewrites = False
    n1 = int(ctx.sql("SELECT count(*) AS n FROM ev")["n"][0])
    ctx.append_rows("ev", [{"city": "austin", "year": 1997, "qty": 1,
                            "rev": 0.0, "ts": T0}])
    n2 = int(ctx.sql("SELECT count(*) AS n FROM ev")["n"][0])
    assert n2 == n1 + 1
    # a novel value changes the dictionary: decoded frames must follow
    ctx.append_rows("ev", [{"city": "xylopolis", "year": 1997, "qty": 1,
                            "rev": 0.0, "ts": T0}])
    got = ctx.sql("SELECT count(*) AS n FROM ev WHERE city = 'xylopolis'")
    assert int(got["n"][0]) == 1


# ---------------------------------------------------------------------------
# review-hardening regressions (PR 6 code review)
# ---------------------------------------------------------------------------


def test_sweep_compacts_tiny_append_trickle_by_segment_count():
    """A 1-row-per-append trickle accretes padded SEGMENTS, not rows: the
    sweep must gate on segment count too, or memory grows 1024x the data
    while staying under the row threshold forever."""
    rng = np.random.default_rng(19)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", _rows(2000, rng))
    ctx.compactor.min_delta_rows = 1 << 20  # row gate never fires
    ctx.compactor.min_delta_segments = 8
    for i in range(8):
        ctx.append_rows("ev", [{"city": "austin", "year": 1997, "qty": 1,
                                "rev": 0.0, "ts": T0 + i * DAY}])
    assert len(ctx.catalog.get("ev").delta_segments()) == 8
    done = ctx.compactor.run_pending()
    assert done and done[0]["compacted_rows"] == 8
    assert ctx.catalog.get("ev").delta_segments() == ()


def test_append_rejects_null_time_values():
    rng = np.random.default_rng(20)
    ctx = sd.TPUOlapContext()
    _register(ctx, "ev", _rows(1000, rng))
    with pytest.raises(ValueError, match="time column"):
        ctx.append_rows("ev", [{"city": "austin", "year": 1997, "qty": 1,
                                "rev": 0.0, "ts": None}])
    # one null among valid rows is equally rejected (no silent NaT row)
    with pytest.raises(ValueError, match="time column"):
        ctx.append_rows("ev", {"city": ["a", "b"], "year": [1995, 1996],
                               "qty": [1, 2], "rev": [0.1, 0.2],
                               "ts": [T0, None]})


def test_register_datasource_returns_version_stamped_snapshot():
    rng = np.random.default_rng(21)
    cols = _rows(1000, rng)
    ds = build_datasource("t", cols, ["city", "year"], ["qty", "rev"],
                          time_col="ts")
    ctx = sd.TPUOlapContext()
    out = ctx.register_datasource(ds)
    assert out.version == ctx.catalog.datasource_version("t") == 1
    ack = ctx.append_rows("t", [{"city": "austin", "year": 1997, "qty": 1,
                                 "rev": 0.0, "ts": T0}])
    assert ack["datasourceVersion"] == out.version + 1


def test_http_ingest_tolerates_malformed_timeout(served):
    ctx, srv = served
    status, ack, _ = _post(
        srv, "/druid/v2/ingest/ev",
        {"rows": [{"city": "austin", "year": 1997, "qty": 1, "rev": 0.0,
                   "ts": T0}],
         "context": {"timeout": None}},
    )
    assert status == 200 and ack["appended"] == 1


def test_extend_dict_large_domain_is_fast_and_exact():
    """The old->new LUT is vectorized (the per-value code_of loop was
    O(card^2) on string domains)."""
    import time as _time

    big = DimensionDict(values=tuple("v%07d" % i for i in range(200_000)))
    t0 = _time.perf_counter()
    new, lut = extend_dict(big, ["a_novel_value"])
    took = _time.perf_counter() - t0
    assert took < 2.0, f"extend_dict took {took:.1f}s on a 200K domain"
    assert new.cardinality == big.cardinality + 1
    assert new.values[0] == "a_novel_value"
    np.testing.assert_array_equal(lut, np.arange(1, 200_001))


# ---------------------------------------------------------------------------
# per-file CSV shard source (ISSUE 10 satellite: ROADMAP 2(a) remainder)
# ---------------------------------------------------------------------------


def _write_csv_files(tmp_path, n_files=3, rows=400, seed=5):
    rng = np.random.default_rng(seed)
    frames = []
    paths = []
    base = 0
    for i in range(n_files):
        df = pd.DataFrame(
            {
                "ts": (base + np.arange(rows)) * 1_000,
                "city": rng.choice(
                    ["austin", "boston", f"only_in_{i}", "dallas"], rows
                ),
                "qty": rng.integers(1, 9, rows),
                "rev": np.round(rng.random(rows), 3),
            }
        )
        base += rows
        p = tmp_path / f"part_{i}.csv"
        df.to_csv(p, index=False)
        frames.append(df)
        paths.append(str(p))
    return paths, pd.concat(frames, ignore_index=True)


def test_csv_per_file_shard_source_matches_serial(tmp_path):
    """build_datasource_from_csv: each file's native decode IS a phase-1
    factorize shard — merged dictionaries, remapped codes, and segment
    rows must equal the one-big-frame serial build exactly."""
    from spark_druid_olap_tpu.ingest.shard import (
        build_datasource_from_csv,
        build_datasource_sharded,
    )

    paths, merged = _write_csv_files(tmp_path)
    ds = build_datasource_from_csv(
        "csvsrc", paths, ["city"], ["qty", "rev"],
        time_col="ts", rows_per_segment=256,
    )
    want = build_datasource_sharded(
        "csvser",
        {c: merged[c].values for c in merged.columns},
        ["city"], ["qty", "rev"],
        time_col="ts", rows_per_segment=256, workers=1,
    )
    assert ds.dicts["city"].values == want.dicts["city"].values
    assert len(ds.segments) == len(want.segments)
    for a, b in zip(ds.segments, want.segments):
        assert a.num_rows == b.num_rows
        np.testing.assert_array_equal(
            np.asarray(a.dims["city"]), np.asarray(b.dims["city"])
        )
        for m in ("qty", "rev"):
            np.testing.assert_array_equal(
                np.asarray(a.column(m)), np.asarray(b.column(m))
            )


def test_csv_shard_source_queryable_with_oracle_parity(tmp_path):
    from spark_druid_olap_tpu.ingest.shard import build_datasource_from_csv

    paths, merged = _write_csv_files(tmp_path, n_files=2, rows=300)
    ds = build_datasource_from_csv(
        "csvq", paths, ["city"], ["qty", "rev"],
        time_col="ts", rows_per_segment=128,
    )
    ctx = sd.TPUOlapContext()
    ctx.catalog.put(ds)
    got = ctx.sql(
        "SELECT city, SUM(qty) AS q, COUNT(*) AS n FROM csvq "
        "GROUP BY city"
    ).sort_values("city").reset_index(drop=True)
    want = (
        merged.groupby("city")
        .agg(q=("qty", "sum"), n=("qty", "count"))
        .reset_index()
        .sort_values("city")
        .reset_index(drop=True)
    )
    assert list(got["city"]) == list(want["city"])
    np.testing.assert_array_equal(
        got["q"].astype(np.int64), want["q"].astype(np.int64)
    )
    np.testing.assert_array_equal(
        got["n"].astype(np.int64), want["n"].astype(np.int64)
    )


def test_csv_shard_source_caller_dict_reencodes(tmp_path):
    """A caller-supplied dictionary wins: native per-file rank codes are
    decoded back to values and re-encoded under the caller's domain
    (codes are ranks over the FILE's domain, never reinterpretable)."""
    from spark_druid_olap_tpu.ingest.shard import build_datasource_from_csv

    paths, merged = _write_csv_files(tmp_path, n_files=2, rows=200)
    domain = tuple(
        sorted(set(map(str, merged["city"])) | {"zz_unused"})
    )
    ds = build_datasource_from_csv(
        "csvd", paths, ["city"], ["qty"],
        time_col="ts", rows_per_segment=128,
        dicts={"city": DimensionDict(values=domain)},
    )
    assert ds.dicts["city"].values == domain
    decoded = np.concatenate(
        [
            ds.dicts["city"].decode(
                np.asarray(s.dims["city"])[: s.num_rows]
            )
            for s in ds.segments
        ]
    )
    np.testing.assert_array_equal(
        decoded, merged["city"].astype(str).values
    )
