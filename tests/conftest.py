"""Test harness: force an 8-device CPU mesh so multi-chip sharding paths run
without TPU hardware (SURVEY.md §4: the fake multi-node backend the reference
never had — its tests demanded a live Druid cluster; ours demand nothing)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The TPU plugin (axon) registers itself from sitecustomize at interpreter
# startup, so jax is already imported and env-var overrides are too late —
# switch platform via jax.config before any backend initializes.
import jax

if os.environ.get("SDOL_TEST_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from spark_druid_olap_tpu.catalog.segment import build_datasource
from spark_druid_olap_tpu.utils import datagen


@pytest.fixture(scope="session")
def lineitem_ds():
    cols = datagen.gen_lineitem(scale=0.005, seed=42)  # ~30k rows
    return build_datasource(
        "tpch",
        cols,
        dimension_cols=datagen.LINEITEM_DIMS,
        metric_cols=datagen.LINEITEM_METRICS,
        time_col="l_shipdate",
        rows_per_segment=8192,  # several segments to exercise merge
    )


@pytest.fixture(scope="session")
def lineitem_cols():
    return datagen.gen_lineitem(scale=0.005, seed=42)


@pytest.fixture(scope="session")
def ssb_ds():
    cols = datagen.gen_ssb_lineorder_flat(scale=0.005, seed=7)
    return build_datasource(
        "ssb",
        cols,
        dimension_cols=datagen.SSB_DIMS,
        metric_cols=datagen.SSB_METRICS,
        time_col="lo_orderdate",
        rows_per_segment=16384,
    )


@pytest.fixture(scope="session")
def ssb_cols():
    return datagen.gen_ssb_lineorder_flat(scale=0.005, seed=7)
