"""L7 serving surface: native Druid queries and SQL over HTTP, plus wire
round-trip of query JSON (VERDICT r1 missing #8 / SURVEY.md §1 L7)."""

import json
import urllib.request

import numpy as np
import pandas as pd
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.models.wire import query_from_druid
from spark_druid_olap_tpu.server import OlapServer


@pytest.fixture(scope="module")
def served():
    ctx = sd.TPUOlapContext()
    n = 10_000
    rng = np.random.default_rng(9)
    city = rng.choice(np.array(["NY", "SF", "LA", "CHI"], dtype=object), n)
    ts = (
        np.datetime64("2021-01-01", "ms").astype(np.int64)
        + rng.integers(0, 60, n) * 86_400_000
    )
    ctx.register_table(
        "ev",
        {
            "city": city,
            "v": rng.random(n).astype(np.float32),
            "k": rng.integers(0, 500, n).astype(np.int64),
            "ts": ts,
        },
        dimensions=["city"],
        metrics=["v", "k"],
        time_column="ts",
    )
    srv = OlapServer(ctx, port=0).start()
    yield ctx, srv, pd.DataFrame(
        {
            "city": city,
            "v": np.asarray(
                ctx.catalog.get("ev").segments[0].metrics["v"][:n], np.float64
            ),
        }
    )
    srv.shutdown()


def _get(srv, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{srv.port}{path}", timeout=30
    ) as r:
        return json.loads(r.read())


def _post(srv, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_health_and_metadata(served):
    _, srv, _ = served
    assert _get(srv, "/status/health") is True
    assert _get(srv, "/druid/v2/datasources") == ["ev"]
    meta = _get(srv, "/druid/v2/datasources/ev")
    assert meta["dimensions"] == ["city"]
    assert set(meta["metrics"]) == {"v", "k"}
    assert meta["numRows"] == 10_000


def test_native_groupby_query(served):
    ctx, srv, df = served
    q = {
        "queryType": "groupBy",
        "dataSource": "ev",
        "granularity": "all",
        "dimensions": [{"type": "default", "dimension": "city"}],
        "aggregations": [
            {"type": "doubleSum", "name": "s", "fieldName": "v"},
            {"type": "count", "name": "n"},
        ],
    }
    code, out = _post(srv, "/druid/v2", q)
    assert code == 200
    events = {r["event"]["city"]: r["event"] for r in out}
    want = df.groupby("city").agg(s=("v", "sum"), n=("v", "count"))
    assert set(events) == set(want.index)
    for city, ev in events.items():
        assert ev["n"] == int(want.loc[city, "n"])
        np.testing.assert_allclose(ev["s"], want.loc[city, "s"], rtol=2e-5)
    assert all(r["version"] == "v1" for r in out)


def test_native_topn_and_timeseries(served):
    ctx, srv, df = served
    code, out = _post(
        srv,
        "/druid/v2",
        {
            "queryType": "topN",
            "dataSource": "ev",
            "dimension": {"type": "default", "dimension": "city"},
            "metric": "s",
            "threshold": 2,
            "aggregations": [
                {"type": "doubleSum", "name": "s", "fieldName": "v"}
            ],
        },
    )
    assert code == 200 and len(out[0]["result"]) == 2
    want_top = df.groupby("city")["v"].sum().sort_values(ascending=False)
    assert out[0]["result"][0]["city"] == want_top.index[0]

    code, ts = _post(
        srv,
        "/druid/v2",
        {
            "queryType": "timeseries",
            "dataSource": "ev",
            "granularity": "day",
            "aggregations": [{"type": "count", "name": "n"}],
            "context": {"skipEmptyBuckets": True},
        },
    )
    assert code == 200
    assert sum(r["result"]["n"] for r in ts) == len(df)


def test_sql_endpoint(served):
    ctx, srv, df = served
    code, rows = _post(
        srv,
        "/druid/v2/sql",
        {"query": "SELECT city, count(*) AS n FROM ev GROUP BY city ORDER BY city"},
    )
    assert code == 200
    want = df.groupby("city").size().sort_index()
    assert [r["city"] for r in rows] == list(want.index)
    assert [r["n"] for r in rows] == [int(x) for x in want]


def test_error_shapes(served):
    _, srv, _ = served
    code, out = _post(srv, "/druid/v2", {"queryType": "groupBy", "dataSource": "nope",
                                         "dimensions": [], "aggregations": []})
    assert code == 400 and "unknown dataSource" in out["error"]
    code, out = _post(srv, "/druid/v2", {"queryType": "mystery"})
    assert code == 400
    code, out = _post(srv, "/druid/v2/sql", {"query": "SELEC bogus"})
    assert code == 500 or code == 400


def test_wire_roundtrip_through_planner(served):
    """Planner output JSON -> wire decoder -> engine must equal ctx.sql."""
    ctx, srv, df = served
    sql = (
        "SELECT city, sum(v) AS s, count(*) AS n FROM ev "
        "WHERE city <> 'LA' GROUP BY city"
    )
    rw = ctx.plan_sql(sql)
    q2 = query_from_druid(rw.query.to_druid())
    got = ctx.engine.execute(q2, ctx.catalog.get("ev"))
    want = ctx.sql(sql)
    got = got.sort_values("city").reset_index(drop=True)[["city", "s", "n"]]
    want = want.sort_values("city").reset_index(drop=True)[["city", "s", "n"]]
    pd.testing.assert_frame_equal(got, want)


def test_wire_roundtrip_expression_agg(served):
    ctx, srv, _ = served
    sql = "SELECT city, sum(v * 2) AS d FROM ev GROUP BY city"
    rw = ctx.plan_sql(sql)
    q2 = query_from_druid(rw.query.to_druid())
    got = ctx.engine.execute(q2, ctx.catalog.get("ev"))
    want = ctx.sql(sql)
    np.testing.assert_allclose(
        np.sort(np.asarray(got["d"])), np.sort(np.asarray(want["d"])), rtol=2e-5
    )


def test_status_metrics_after_query(served):
    ctx, srv, _ = served
    _post(srv, "/druid/v2/sql", {"query": "SELECT count(*) AS n FROM ev"})
    st = _get(srv, "/status")
    assert st["last_query_metrics"] is not None
    assert st["last_query_metrics"]["rows_scanned"] == 10_000
