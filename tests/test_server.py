"""L7 serving surface: native Druid queries and SQL over HTTP, plus wire
round-trip of query JSON (VERDICT r1 missing #8 / SURVEY.md §1 L7)."""

import json
import urllib.request

import numpy as np
import pandas as pd
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.models.wire import query_from_druid
from spark_druid_olap_tpu.server import OlapServer


@pytest.fixture(scope="module")
def served():
    ctx = sd.TPUOlapContext()
    n = 10_000
    rng = np.random.default_rng(9)
    city = rng.choice(np.array(["NY", "SF", "LA", "CHI"], dtype=object), n)
    ts = (
        np.datetime64("2021-01-01", "ms").astype(np.int64)
        + rng.integers(0, 60, n) * 86_400_000
    )
    ctx.register_table(
        "ev",
        {
            "city": city,
            "v": rng.random(n).astype(np.float32),
            "k": rng.integers(0, 500, n).astype(np.int64),
            "ts": ts,
        },
        dimensions=["city"],
        metrics=["v", "k"],
        time_column="ts",
    )
    srv = OlapServer(ctx, port=0).start()
    yield ctx, srv, pd.DataFrame(
        {
            "city": city,
            "v": np.asarray(
                ctx.catalog.get("ev").segments[0].metrics["v"][:n], np.float64
            ),
        }
    )
    srv.shutdown()


def _get(srv, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{srv.port}{path}", timeout=30
    ) as r:
        return json.loads(r.read())


def _post(srv, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_health_and_metadata(served):
    _, srv, _ = served
    health = _get(srv, "/status/health")
    assert health["healthy"] is True
    assert health["breaker"]["state"] == "closed"
    assert health["admission"]["slots_in_use"] == 0
    assert health["admission"]["slots_total"] >= 1
    assert _get(srv, "/druid/v2/datasources") == ["ev"]
    meta = _get(srv, "/druid/v2/datasources/ev")
    assert meta["dimensions"] == ["city"]
    assert set(meta["metrics"]) == {"v", "k"}
    assert meta["numRows"] == 10_000


def test_native_groupby_query(served):
    ctx, srv, df = served
    q = {
        "queryType": "groupBy",
        "dataSource": "ev",
        "granularity": "all",
        "dimensions": [{"type": "default", "dimension": "city"}],
        "aggregations": [
            {"type": "doubleSum", "name": "s", "fieldName": "v"},
            {"type": "count", "name": "n"},
        ],
    }
    code, out = _post(srv, "/druid/v2", q)
    assert code == 200
    events = {r["event"]["city"]: r["event"] for r in out}
    want = df.groupby("city").agg(s=("v", "sum"), n=("v", "count"))
    assert set(events) == set(want.index)
    for city, ev in events.items():
        assert ev["n"] == int(want.loc[city, "n"])
        np.testing.assert_allclose(ev["s"], want.loc[city, "s"], rtol=2e-5)
    assert all(r["version"] == "v1" for r in out)


def test_native_topn_and_timeseries(served):
    ctx, srv, df = served
    code, out = _post(
        srv,
        "/druid/v2",
        {
            "queryType": "topN",
            "dataSource": "ev",
            "dimension": {"type": "default", "dimension": "city"},
            "metric": "s",
            "threshold": 2,
            "aggregations": [
                {"type": "doubleSum", "name": "s", "fieldName": "v"}
            ],
        },
    )
    assert code == 200 and len(out[0]["result"]) == 2
    want_top = df.groupby("city")["v"].sum().sort_values(ascending=False)
    assert out[0]["result"][0]["city"] == want_top.index[0]

    code, ts = _post(
        srv,
        "/druid/v2",
        {
            "queryType": "timeseries",
            "dataSource": "ev",
            "granularity": "day",
            "aggregations": [{"type": "count", "name": "n"}],
            "context": {"skipEmptyBuckets": True},
        },
    )
    assert code == 200
    assert sum(r["result"]["n"] for r in ts) == len(df)


def test_sql_endpoint(served):
    ctx, srv, df = served
    code, rows = _post(
        srv,
        "/druid/v2/sql",
        {"query": "SELECT city, count(*) AS n FROM ev GROUP BY city ORDER BY city"},
    )
    assert code == 200
    want = df.groupby("city").size().sort_index()
    assert [r["city"] for r in rows] == list(want.index)
    assert [r["n"] for r in rows] == [int(x) for x in want]


def test_error_shapes(served):
    _, srv, _ = served
    code, out = _post(srv, "/druid/v2", {"queryType": "groupBy", "dataSource": "nope",
                                         "dimensions": [], "aggregations": []})
    assert code == 400 and "unknown dataSource" in out["error"]
    code, out = _post(srv, "/druid/v2", {"queryType": "mystery"})
    assert code == 400
    code, out = _post(srv, "/druid/v2/sql", {"query": "SELEC bogus"})
    assert code == 500 or code == 400


def test_wire_roundtrip_through_planner(served):
    """Planner output JSON -> wire decoder -> engine must equal ctx.sql."""
    ctx, srv, df = served
    sql = (
        "SELECT city, sum(v) AS s, count(*) AS n FROM ev "
        "WHERE city <> 'LA' GROUP BY city"
    )
    rw = ctx.plan_sql(sql)
    q2 = query_from_druid(rw.query.to_druid())
    got = ctx.engine.execute(q2, ctx.catalog.get("ev"))
    want = ctx.sql(sql)
    got = got.sort_values("city").reset_index(drop=True)[["city", "s", "n"]]
    want = want.sort_values("city").reset_index(drop=True)[["city", "s", "n"]]
    pd.testing.assert_frame_equal(got, want)


def test_wire_roundtrip_expression_agg(served):
    ctx, srv, _ = served
    sql = "SELECT city, sum(v * 2) AS d FROM ev GROUP BY city"
    rw = ctx.plan_sql(sql)
    q2 = query_from_druid(rw.query.to_druid())
    got = ctx.engine.execute(q2, ctx.catalog.get("ev"))
    want = ctx.sql(sql)
    np.testing.assert_allclose(
        np.sort(np.asarray(got["d"])), np.sort(np.asarray(want["d"])), rtol=2e-5
    )


def test_status_metrics_after_query(served):
    ctx, srv, _ = served
    _post(srv, "/druid/v2/sql", {"query": "SELECT count(*) AS n FROM ev"})
    st = _get(srv, "/status")
    assert st["last_query_metrics"] is not None
    assert st["last_query_metrics"]["rows_scanned"] == 10_000


def test_time_boundary_query(served):
    ctx, srv, frame = served
    code, out = _post(srv, "/druid/v2", {"queryType": "timeBoundary", "dataSource": "ev"})
    assert code == 200 and len(out) == 1
    res = out[0]["result"]
    assert "minTime" in res and "maxTime" in res
    assert res["minTime"].startswith("2021-01-01")
    # bound=maxTime returns only the max
    code, out = _post(
        srv, "/druid/v2",
        {"queryType": "timeBoundary", "dataSource": "ev", "bound": "maxTime"},
    )
    assert code == 200
    assert "maxTime" in out[0]["result"] and "minTime" not in out[0]["result"]


def test_datasource_metadata_query(served):
    ctx, srv, frame = served
    code, out = _post(
        srv, "/druid/v2",
        {"queryType": "dataSourceMetadata", "dataSource": "ev"},
    )
    assert code == 200 and len(out) == 1
    res = out[0]["result"]
    assert "maxIngestedEventTime" in res
    # matches the timeBoundary maxTime (same metadata source)
    _, tb = _post(
        srv, "/druid/v2",
        {"queryType": "timeBoundary", "dataSource": "ev", "bound": "maxTime"},
    )
    assert res["maxIngestedEventTime"] == tb[0]["result"]["maxTime"]
    assert out[0]["timestamp"] == res["maxIngestedEventTime"]


def test_segment_metadata_query(served):
    ctx, srv, frame = served
    code, out = _post(
        srv, "/druid/v2", {"queryType": "segmentMetadata", "dataSource": "ev"}
    )
    assert code == 200
    assert len(out) == len(ctx.catalog.get("ev").segments)
    seg = out[0]
    assert seg["numRows"] > 0
    assert seg["columns"]["city"]["type"] == "dimension"
    assert seg["columns"]["city"]["cardinality"] == 4
    assert seg["columns"]["v"]["type"] == "metric"
    assert seg["intervals"] and "/" in seg["intervals"][0]


def test_theta_set_op_post_agg(served):
    """UNION/INTERSECT/NOT estimates over two theta sketches, checked against
    exact set algebra on the generated data."""
    ctx, srv, frame = served
    ds = ctx.catalog.get("ev")
    seg = ds.segments[0]
    k = np.asarray(seg.metrics["k"])[seg.valid]
    city_codes = np.asarray(seg.dims["city"])[seg.valid]
    city_vals = np.asarray(ds.dicts["city"].decode(city_codes), dtype=object)
    ny = set(k[city_vals == "NY"].tolist())
    sf = set(k[city_vals == "SF"].tolist())
    q = {
        "queryType": "groupBy",
        "dataSource": "ev",
        "dimensions": [],
        "granularity": "all",
        "intervals": ["2020-01-01T00:00:00.000Z/2022-01-01T00:00:00.000Z"],
        "aggregations": [
            {"type": "filtered",
             "filter": {"type": "selector", "dimension": "city", "value": "NY"},
             "aggregator": {"type": "thetaSketch", "name": "ny_k", "fieldName": "k", "size": 4096}},
            {"type": "filtered",
             "filter": {"type": "selector", "dimension": "city", "value": "SF"},
             "aggregator": {"type": "thetaSketch", "name": "sf_k", "fieldName": "k", "size": 4096}},
        ],
        "postAggregations": [
            {"type": "thetaSketchEstimate", "name": "union_k",
             "field": {"type": "thetaSketchSetOp", "name": "u", "func": "UNION",
                        "fields": [{"type": "fieldAccess", "fieldName": "ny_k"},
                                   {"type": "fieldAccess", "fieldName": "sf_k"}]}},
            {"type": "thetaSketchEstimate", "name": "inter_k",
             "field": {"type": "thetaSketchSetOp", "name": "i", "func": "INTERSECT",
                        "fields": [{"type": "fieldAccess", "fieldName": "ny_k"},
                                   {"type": "fieldAccess", "fieldName": "sf_k"}]}},
            {"type": "thetaSketchEstimate", "name": "not_k",
             "field": {"type": "thetaSketchSetOp", "name": "n", "func": "NOT",
                        "fields": [{"type": "fieldAccess", "fieldName": "ny_k"},
                                   {"type": "fieldAccess", "fieldName": "sf_k"}]}},
        ],
    }
    code, out = _post(srv, "/druid/v2", q)
    assert code == 200, out
    ev = out[0]["event"]
    # 500-value domain, K=4096 slots: sketches are exact below K (bar 32-bit
    # hash collisions, negligible at this size)
    assert abs(ev["union_k"] - len(ny | sf)) <= 2
    assert abs(ev["inter_k"] - len(ny & sf)) <= 2
    assert abs(ev["not_k"] - len(ny - sf)) <= 2


def test_eternity_interval_spellings_decode_to_no_constraint():
    """Eternity must be detected by parsed bounds, not string equality: a
    real Druid client sends the canonical Long.MIN/MAX spelling (six-digit
    years), others send milliless variants — none may turn into a real time
    filter (which would demand a time column) or crash the ISO parser."""
    from spark_druid_olap_tpu.models.wire import intervals_from_druid

    for iv in (
        "0000-01-01T00:00:00.000Z/3000-01-01T00:00:00.000Z",  # our spelling
        "0000-01-01T00:00:00Z/3000-01-01T00:00:00Z",  # no millis
        "-146136543-09-08T08:23:32.096Z/146140482-04-24T15:36:27.903Z",
    ):
        assert intervals_from_druid([iv]) == (), iv
    # a real interval still decodes to real bounds
    (got,) = intervals_from_druid(["2024-01-01T00:00:00Z/2024-02-01T00:00:00Z"])
    import numpy as np

    assert got[0] == int(np.datetime64("2024-01-01", "ms").astype(np.int64))
    assert got[1] == int(np.datetime64("2024-02-01", "ms").astype(np.int64))


def test_far_future_interval_stays_a_real_interval():
    """A genuine interval at/past the year-3000 sentinel must keep its real
    bounds (only true eternity decodes to no-constraint)."""
    import numpy as np

    from spark_druid_olap_tpu.models.wire import intervals_from_druid

    (got,) = intervals_from_druid(["3500-01-01T00:00:00Z/3600-01-01T00:00:00Z"])
    assert got[0] == int(np.datetime64("3500-01-01", "ms").astype(np.int64))
    assert got[1] == int(np.datetime64("3600-01-01", "ms").astype(np.int64))
    (got2,) = intervals_from_druid(["2999-06-01T00:00:00Z/3500-01-01T00:00:00Z"])
    assert got2[1] == int(np.datetime64("3500-01-01", "ms").astype(np.int64))


def test_native_groupby_having_honored(served):
    """A wire groupBy's havingSpec must filter result rows (not be silently
    dropped)."""
    ctx, srv, df = served
    body = {
        "queryType": "groupBy",
        "dataSource": "ev",
        "dimensions": ["city"],
        "aggregations": [{"type": "count", "name": "n"}],
        "granularity": "all",
        "intervals": ["0000-01-01T00:00:00.000Z/3000-01-01T00:00:00.000Z"],
    }
    status, rows = _post(srv, "/druid/v2", body)
    assert status == 200 and len(rows) == 4
    counts = sorted(r["event"]["n"] for r in rows)
    threshold = counts[1]  # cut between the 2nd and 3rd city
    body["having"] = {
        "type": "greaterThan", "aggregation": "n", "value": threshold,
    }
    status, rows2 = _post(srv, "/druid/v2", body)
    assert status == 200
    assert 0 < len(rows2) < 4
    assert all(r["event"]["n"] > threshold for r in rows2)
    # NOT wrapping a compound spec (our serializer never emits this shape;
    # a Druid client can)
    body["having"] = {
        "type": "not",
        "havingSpec": {
            "type": "or",
            "havingSpecs": [
                {"type": "greaterThan", "aggregation": "n", "value": threshold},
                {"type": "lessThan", "aggregation": "n", "value": 1},
            ],
        },
    }
    status, rows3 = _post(srv, "/druid/v2", body)
    assert status == 200
    assert all(1 <= r["event"]["n"] <= threshold for r in rows3)
    assert len(rows2) + len(rows3) == 4


def test_native_groupby_subtotals_spec(served):
    """A wire groupBy's subtotalsSpec expands into grouping sets (the SQL
    CUBE path), not just the full grouping."""
    ctx, srv, df = served
    body = {
        "queryType": "groupBy",
        "dataSource": "ev",
        "dimensions": ["city"],
        "aggregations": [{"type": "count", "name": "n"}],
        "granularity": "all",
        "intervals": ["0000-01-01T00:00:00.000Z/3000-01-01T00:00:00.000Z"],
        "subtotalsSpec": [["city"], []],
    }
    status, rows = _post(srv, "/druid/v2", body)
    assert status == 200
    assert len(rows) == 5  # 4 cities + 1 grand total
    totals = [r["event"] for r in rows if r["event"]["city"] is None]
    assert len(totals) == 1
    per_city = [r["event"]["n"] for r in rows if r["event"]["city"] is not None]
    assert totals[0]["n"] == sum(per_city)
    # no internal bookkeeping columns leak onto the wire
    assert all("__grouping_id" not in r["event"] for r in rows)
    # a limitSpec orderBy applies to the COMBINED result (and must not
    # crash the sets that aggregate the orderBy dimension away)
    body["limitSpec"] = {
        "type": "default",
        "columns": [{"dimension": "n", "direction": "descending"}],
        "limit": 3,
    }
    status, rows_l = _post(srv, "/druid/v2", body)
    assert status == 200 and len(rows_l) == 3
    ns = [r["event"]["n"] for r in rows_l]
    assert ns == sorted(ns, reverse=True)
    assert rows_l[0]["event"]["city"] is None  # grand total tops the sort
    del body["limitSpec"]
    # unknown dimension name in subtotalsSpec is a 400, not a silent drop
    body["subtotalsSpec"] = [["nope"]]
    status, err = _post(srv, "/druid/v2", body)
    assert status == 400


def test_topn_dimension_metric(served):
    """Druid's dimension-ordered topN (lexicographic ranking by the
    dimension value itself) must be honored, both orderings."""
    ctx, srv, df = served
    body = {
        "queryType": "topN",
        "dataSource": "ev",
        "dimension": "city",
        "metric": {"type": "dimension", "ordering": "lexicographic"},
        "threshold": 3,
        "aggregations": [{"type": "count", "name": "n"}],
        "granularity": "all",
        "intervals": ["0000-01-01T00:00:00.000Z/3000-01-01T00:00:00.000Z"],
    }
    status, out = _post(srv, "/druid/v2", body)
    assert status == 200
    rows = out[0]["result"]
    cities = [r["city"] for r in rows]
    assert cities == sorted(set(df["city"]))[:3]
    # descending dimension order is Druid's inverted-wrapped form
    body["metric"] = {
        "type": "inverted",
        "metric": {"type": "dimension", "ordering": "lexicographic"},
    }
    status, out2 = _post(srv, "/druid/v2", body)
    assert status == 200
    cities2 = [r["city"] for r in out2[0]["result"]]
    assert cities2 == sorted(set(df["city"]), reverse=True)[:3]
    # an unsupported metric spec type is a clean 400
    body["metric"] = {"type": "nope"}
    status, err = _post(srv, "/druid/v2", body)
    assert status == 400


def test_expression_post_aggregator(served):
    """Druid `expression` post-aggregators evaluate over result columns and
    round-trip through the wire."""
    ctx, srv, df = served
    body = {
        "queryType": "groupBy",
        "dataSource": "ev",
        "dimensions": ["city"],
        "aggregations": [
            {"type": "doubleSum", "name": "s", "fieldName": "v"},
            {"type": "count", "name": "n"},
        ],
        "postAggregations": [
            {"type": "expression", "name": "ratio", "expression": "s / n"},
        ],
        "granularity": "all",
        "intervals": ["0000-01-01T00:00:00.000Z/3000-01-01T00:00:00.000Z"],
    }
    status, rows = _post(srv, "/druid/v2", body)
    assert status == 200
    for r in rows:
        ev = r["event"]
        np.testing.assert_allclose(ev["ratio"], ev["s"] / ev["n"], rtol=1e-6)
    # round-trip: stable after one normalization pass (plain-string
    # dimensions acquire an explicit outputName on first decode)
    q = query_from_druid(query_from_druid(body).to_druid())
    assert query_from_druid(q.to_druid()) == q
    # a malformed expression is a 400
    body["postAggregations"] = [
        {"type": "expression", "name": "bad", "expression": "s +"}
    ]
    status, err = _post(srv, "/druid/v2", body)
    assert status == 400


def test_expression_post_agg_edge_cases(served):
    ctx, srv, df = served
    base = {
        "queryType": "groupBy",
        "dataSource": "ev",
        "dimensions": ["city"],
        "aggregations": [
            {"type": "doubleSum", "name": "s", "fieldName": "v"},
            {"type": "count", "name": "n"},
        ],
        "granularity": "all",
        "intervals": ["0000-01-01T00:00:00.000Z/3000-01-01T00:00:00.000Z"],
    }
    # trailing garbage must be rejected, not silently truncated
    body = dict(base)
    body["postAggregations"] = [
        {"type": "expression", "name": "x", "expression": "s * 2 bogus"}
    ]
    status, err = _post(srv, "/druid/v2", body)
    assert status == 400 and "trailing" in err["error"]
    # lexer-level garbage is also a 400, not a 500
    body["postAggregations"] = [
        {"type": "expression", "name": "x", "expression": "s | 2"}
    ]
    status, err = _post(srv, "/druid/v2", body)
    assert status == 400
    # CASE round-trips (serializes as if(...), which the grammar accepts)
    body["postAggregations"] = [
        {
            "type": "expression",
            "name": "flag",
            "expression": "case when s > 0 then 1 else 0 end",
        }
    ]
    status, rows = _post(srv, "/druid/v2", body)
    assert status == 200
    assert all(r["event"]["flag"] == 1 for r in rows)
    q = query_from_druid(query_from_druid(body).to_druid())
    assert query_from_druid(q.to_druid()) == q


def test_sort_by_with_nulls():
    c = sd.TPUOlapContext()
    c.register_table(
        "ns",
        {
            "c": np.array(["b", None, "a", "b", None], dtype=object),
            "v": np.arange(5, dtype=np.float32),
        },
        dimensions=["c"],
        metrics=["v"],
        sort_by=["c"],
        rows_per_segment=2,
    )
    # grouping is intact after the null-safe sort
    got = c.sql("SELECT c, count(*) AS n, sum(v) AS s FROM ns GROUP BY c")
    by = {row["c"]: row for _, row in got.iterrows()}
    assert by["a"]["n"] == 1 and by["a"]["s"] == 2.0
    assert by["b"]["n"] == 2 and by["b"]["s"] == 0.0 + 3.0
    null_row = got[got["c"].isna()].iloc[0]
    assert null_row["n"] == 2 and null_row["s"] == 1.0 + 4.0
    # nulls-last contract: the physical row order is a, b, b, null, null
    ds = c.catalog.get("ns")
    codes = np.concatenate(
        [np.asarray(s.dims["c"])[s.valid] for s in ds.segments]
    )
    nulls = codes < 0
    assert not nulls[:3].any() and nulls[3:].all()
    assert list(codes[:3]) == sorted(codes[:3])


def test_search_expression_columncomparison_filters(served):
    """Round-3 wire filters: search (contains / insensitiveContains),
    expression, interval, columnComparison."""
    ctx, srv, frame = served
    base = {
        "queryType": "timeseries",
        "dataSource": "ev",
        "granularity": "all",
        "aggregations": [{"type": "count", "name": "n"}],
    }
    code, out = _post(
        srv, "/druid/v2",
        {**base, "filter": {
            "type": "search", "dimension": "city",
            "query": {"type": "contains", "value": "F"},
        }},
    )
    assert code == 200
    assert out[0]["result"]["n"] == int((frame["city"] == "SF").sum())
    code, out = _post(
        srv, "/druid/v2",
        {**base, "filter": {
            "type": "search", "dimension": "city",
            "query": {"type": "insensitiveContains", "value": "f"},
        }},
    )
    assert code == 200
    assert out[0]["result"]["n"] == int((frame["city"] == "SF").sum())
    code, out = _post(
        srv, "/druid/v2",
        {**base, "filter": {"type": "expression", "expression": "v > 0.5"}},
    )
    assert code == 200
    assert out[0]["result"]["n"] == int((frame["v"] > 0.5).sum())
    code, out = _post(
        srv, "/druid/v2",
        {**base, "filter": {
            "type": "interval", "dimension": "__time",
            "intervals": ["2021-01-01T00:00:00.000Z/2021-01-08T00:00:00.000Z"],
        }},
    )
    assert code == 200 and out[0]["result"]["n"] > 0


def test_column_comparison_filter_decode():
    from spark_druid_olap_tpu.models.filters import (
        ExpressionFilter,
        filter_from_druid,
    )

    f = filter_from_druid(
        {"type": "columnComparison", "dimensions": ["a", "b"]}
    )
    assert isinstance(f, ExpressionFilter)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="two plain dimensions"):
        filter_from_druid(
            {"type": "columnComparison", "dimensions": ["a"]}
        )


def test_sql_endpoint_round3_surface(served):
    """Windows, set operations, and views all reach the HTTP SQL endpoint
    (they execute on the host fallback behind the same ctx.sql path)."""
    ctx, srv, df = served
    code, rows = _post(
        srv, "/druid/v2/sql",
        {"query": "SELECT city, sum(v) AS s, "
                  "RANK() OVER (ORDER BY sum(v) DESC) AS r "
                  "FROM ev GROUP BY city"},
    )
    assert code == 200
    by_rank = sorted(rows, key=lambda r: r["r"])
    want = df.groupby("city")["v"].sum().sort_values(ascending=False)
    assert [r["city"] for r in by_rank] == list(want.index)
    code, rows = _post(
        srv, "/druid/v2/sql",
        {"query": "SELECT city FROM ev WHERE v > 0.9 "
                  "INTERSECT SELECT city FROM ev WHERE v < 0.1"},
    )
    assert code == 200 and len(rows) == 4  # all four cities span both tails
    code, _ = _post(
        srv, "/druid/v2/sql",
        {"query": "CREATE VIEW hot AS SELECT city, v FROM ev WHERE v > 0.5"},
    )
    assert code == 200
    code, rows = _post(
        srv, "/druid/v2/sql",
        {"query": "SELECT count(*) AS n FROM hot"},
    )
    assert code == 200
    assert rows[0]["n"] == int((df["v"] > 0.5).sum())
