"""SSB star-schema suite: joined SQL -> star-join elimination -> kernels,
parity-checked against a float64 pandas oracle on the same data.

The analog of the reference's StarSchemaTest/JoinTest + SSB benchmark suites
(SURVEY.md §4 `[U]`): every query here is written AS JOINS over the
normalized star; asserting results proves JoinTransform collapsed them onto
the denormalized datasource correctly (SURVEY.md §7 hard part #6)."""

import numpy as np
import pandas as pd
import pytest

from spark_druid_olap_tpu import TPUOlapContext
from spark_druid_olap_tpu.models.query import GroupByQuery
from spark_druid_olap_tpu.plan.planner import RewriteError
from spark_druid_olap_tpu.workloads import ssb


@pytest.fixture(scope="module")
def tables():
    return ssb.gen_tables(scale=0.01, seed=11)


@pytest.fixture(scope="module")
def ctx(tables):
    c = TPUOlapContext()
    ssb.register(c, tables=tables, rows_per_segment=16384)
    return c


@pytest.fixture(scope="module")
def flat(tables):
    return ssb.flat_frame(tables)


def _group_cols(df):
    return [c for c in df.columns if not np.issubdtype(
        np.asarray(df[c]).dtype, np.floating)]


@pytest.mark.parametrize("name", list(ssb.QUERIES))
def test_ssb_query_parity(ctx, flat, name):
    got = ctx.sql(ssb.QUERIES[name])
    want = ssb.oracle(flat, name)
    if isinstance(want, float):  # Q1.x: single-row global aggregate
        np.testing.assert_allclose(got.iloc[0, 0], want, rtol=2e-5)
        return
    value_col = want.columns[-1]
    keys = [c for c in want.columns if c != value_col]
    got_s = got.sort_values(keys).reset_index(drop=True)
    want_s = want.sort_values(keys).reset_index(drop=True)
    assert len(got_s) == len(want_s), (name, len(got_s), len(want_s))
    for k in keys:
        np.testing.assert_array_equal(
            np.asarray(got_s[k]), np.asarray(want_s[k]), err_msg=f"{name}.{k}"
        )
    np.testing.assert_allclose(
        np.asarray(got_s[value_col], np.float64),
        np.asarray(want_s[value_col], np.float64),
        rtol=2e-5, err_msg=name,
    )


def test_star_collapse_in_plan(ctx):
    """The 'plan contains DruidQuery' analog: the joined SSB query rewrites
    to a single GroupBy over the FLAT datasource — no join survives."""
    rw = ctx.plan_sql(ssb.QUERIES["q2_1"])
    assert isinstance(rw.query, GroupByQuery)
    assert rw.datasource == "lineorder"
    assert rw.query.filter is not None


def test_order_by_direction(ctx, flat):
    """q3_1 orders by d_year ASC then revenue DESC — verify the returned
    row order, not just the row set."""
    got = ctx.sql(ssb.QUERIES["q3_1"])
    years = np.asarray(got.d_year)
    assert (np.diff(years) >= 0).all()
    rev = np.asarray(got.revenue)
    for y in np.unique(years):
        r = rev[years == y]
        assert (np.diff(r) <= 1e-6).all(), f"revenue not desc within {y}"


def test_unconforming_join_rejected(ctx):
    """A join NOT declared in the star schema must not be silently
    collapsed — it fails the rewrite (soundness guard)."""
    with pytest.raises(RewriteError):
        ctx.plan_sql(
            "SELECT d_year, count(*) n FROM lineorder "
            "JOIN dwdate ON lo_custkey = d_datekey GROUP BY d_year"
        )


def test_dim_table_directly_queryable(ctx, tables):
    """Dimension tables are ordinary datasources too."""
    got = ctx.sql(
        "SELECT c_region, count(*) n FROM customer GROUP BY c_region "
        "ORDER BY c_region"
    )
    want = pd.Series(tables["customer"]["c_region"]).value_counts().sort_index()
    assert list(got.c_region) == list(want.index)
    np.testing.assert_array_equal(got.n, want.values)
