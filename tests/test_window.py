"""Window functions (OVER clauses) through the host fallback.

Reference parity: the reference never pushed OVER clauses to Druid — every
window function ran as a vanilla Spark plan (SURVEY.md §3.2 fallback
semantics).  Here the parser lifts `fn(...) OVER (PARTITION BY ... ORDER
BY ... [ROWS ...])` into `L.Window` specs; the fallback interpreter
implements SQL semantics: partition-major evaluation, nulls-last ordering
(matching the engine's Sort convention), peer-inclusive default frames
(RANGE UNBOUNDED PRECEDING..CURRENT ROW), bag-exact ROWS frames, and
NULL-skipping window aggregates.  Windows over aggregated results (RANK
over SUM, the top-N-per-group idiom) evaluate above GROUP BY/HAVING.
"""

import numpy as np
import pandas as pd
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.sql.parser import ParseError


@pytest.fixture(scope="module")
def ctx():
    c = sd.TPUOlapContext()
    rng = np.random.default_rng(11)
    n = 400
    g = rng.choice(np.array(["a", "b", "c", None], dtype=object), n)
    s = rng.choice(np.array(["x", "y"], dtype=object), n)
    v = np.where(rng.random(n) < 0.1, np.nan, rng.integers(0, 40, n))
    c.register_table(
        "w",
        {"g": g, "s": s, "v": v.astype(np.float64)},
        dimensions=["g", "s"],
        metrics=["v"],
    )
    c._frame = pd.DataFrame({"g": g, "s": s, "v": v.astype(np.float64)})
    return c


def _ordered(frame, by, asc=True):
    """Partition-ordered frame matching the engine: nulls last, stable."""
    return frame.sort_values(
        by, ascending=asc, kind="stable", na_position="last"
    )


def test_row_number_and_ranks_vs_pandas(ctx):
    got = ctx.sql(
        "SELECT g, v, ROW_NUMBER() OVER (PARTITION BY g ORDER BY v) AS rn, "
        "RANK() OVER (PARTITION BY g ORDER BY v) AS rk, "
        "DENSE_RANK() OVER (PARTITION BY g ORDER BY v) AS dr FROM w"
    )
    f = ctx._frame
    for gval, gdf in f.groupby("g", dropna=False):
        sub = got[
            got["g"].isna() if pd.isna(gval) else (got["g"] == gval)
        ]
        o = _ordered(gdf, "v")
        # pandas rank(method=first) == ROW_NUMBER on non-null; our order
        # puts nulls last, so recompute positions directly
        pos = {idx: i + 1 for i, idx in enumerate(o.index)}
        want_rn = [pos[i] for i in sub.index]
        assert list(sub["rn"]) == want_rn
        # RANK/DENSE_RANK: ties share; NaN rows form their own peer group
        key = o["v"].fillna(np.inf)
        rk, dr, prev = {}, {}, None
        r = d = 0
        for i, (idx, kv) in enumerate(key.items()):
            if prev is None or kv != prev:
                r = i + 1
                d += 1
                prev = kv
            rk[idx], dr[idx] = r, d
        assert list(sub["rk"]) == [rk[i] for i in sub.index]
        assert list(sub["dr"]) == [dr[i] for i in sub.index]


def test_partition_total_and_cumulative(ctx):
    got = ctx.sql(
        "SELECT g, v, SUM(v) OVER (PARTITION BY g) AS tot, "
        "SUM(v) OVER (PARTITION BY g ORDER BY v) AS cum, "
        "COUNT(*) OVER (PARTITION BY g) AS cnt FROM w"
    )
    f = ctx._frame
    for gval, gdf in f.groupby("g", dropna=False):
        sub = got[
            got["g"].isna() if pd.isna(gval) else (got["g"] == gval)
        ]
        t = gdf["v"].sum()
        np.testing.assert_allclose(
            sub["tot"].astype(float), t, rtol=1e-9
        )
        assert (sub["cnt"] == len(gdf)).all()
        # default frame includes peers: cumulative sum at the last peer
        o = _ordered(gdf, "v")
        csum = o["v"].fillna(0).cumsum()
        # peer groups on v (NaNs are peers of each other at the end)
        kv = o["v"].fillna(np.inf)
        cum_at = csum.groupby(kv.values).transform("max")
        want = {idx: cum_at.iloc[i] for i, idx in enumerate(o.index)}
        np.testing.assert_allclose(
            sub["cum"].astype(float),
            [want[i] for i in sub.index],
            rtol=1e-9,
        )


def test_rows_frame_moving_average(ctx):
    got = ctx.sql(
        "SELECT g, v, AVG(v) OVER (PARTITION BY g ORDER BY v "
        "ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS ma FROM w"
    )
    f = ctx._frame
    for gval, gdf in f.groupby("g", dropna=False):
        sub = got[
            got["g"].isna() if pd.isna(gval) else (got["g"] == gval)
        ]
        o = _ordered(gdf, "v")
        vals = o["v"].to_numpy()
        want = {}
        for i, idx in enumerate(o.index):
            window = vals[max(0, i - 2) : i + 1]
            window = window[~np.isnan(window)]
            want[idx] = window.mean() if len(window) else np.nan
        np.testing.assert_allclose(
            sub["ma"].astype(float),
            [want[i] for i in sub.index],
            rtol=1e-9,
        )


def test_lag_lead_defaults(ctx):
    got = ctx.sql(
        "SELECT g, v, LAG(v) OVER (PARTITION BY g ORDER BY v) AS pv, "
        "LEAD(v, 2, -1.0) OVER (PARTITION BY g ORDER BY v) AS nv FROM w"
    )
    f = ctx._frame
    for gval, gdf in f.groupby("g", dropna=False):
        sub = got[
            got["g"].isna() if pd.isna(gval) else (got["g"] == gval)
        ]
        o = _ordered(gdf, "v")
        vals = o["v"].to_numpy()
        pv, nv = {}, {}
        for i, idx in enumerate(o.index):
            pv[idx] = vals[i - 1] if i >= 1 else None
            nv[idx] = vals[i + 2] if i + 2 < len(vals) else -1.0
        for idx in sub.index:
            a, b = sub.loc[idx, "pv"], pv[idx]
            assert (pd.isna(a) and (b is None or pd.isna(b))) or a == b
            a, b = sub.loc[idx, "nv"], nv[idx]
            assert (pd.isna(a) and pd.isna(b)) or a == b


def test_ntile_and_first_last(ctx):
    got = ctx.sql(
        "SELECT v, NTILE(4) OVER (ORDER BY v) AS q, "
        "FIRST_VALUE(v) OVER (ORDER BY v) AS fv, "
        "LAST_VALUE(v) OVER (ORDER BY v ROWS BETWEEN UNBOUNDED "
        "PRECEDING AND UNBOUNDED FOLLOWING) AS lv FROM w"
    )
    n = len(got)
    base, rem = divmod(n, 4)
    sizes = [base + (1 if i < rem else 0) for i in range(4)]
    assert sorted(got["q"].value_counts().reindex([1, 2, 3, 4]).tolist()) \
        == sorted(sizes)
    vmin = ctx._frame["v"].min()
    assert (got["fv"].astype(float) == vmin).all()
    # global last row in nulls-last order is a NaN v -> last_value is NULL
    assert got["lv"].isna().all() or (
        got["lv"].astype(float) == ctx._frame["v"].max()
    ).all()


def test_window_over_aggregates_topn_per_group(ctx):
    """The classic top-N-per-group: rank groups by their aggregate."""
    got = ctx.sql(
        "SELECT g, s, sum(v) AS sv, "
        "RANK() OVER (PARTITION BY g ORDER BY sum(v) DESC) AS r "
        "FROM w GROUP BY g, s ORDER BY g, r"
    )
    f = ctx._frame
    want = (
        f.groupby(["g", "s"], dropna=False)["v"]
        .sum()
        .reset_index(name="sv")
    )
    want["r"] = want.groupby("g", dropna=False)["sv"].rank(
        method="min", ascending=False
    ).astype(int)
    merged = got.merge(
        want, on=["g", "s"], suffixes=("", "_want"), how="left"
    )
    assert len(merged) == len(got) and not merged["r_want"].isna().any()
    np.testing.assert_allclose(
        merged["sv"].astype(float), merged["sv_want"].astype(float),
        rtol=1e-9,
    )
    assert (merged["r"] == merged["r_want"]).all()


def test_window_filter_clause(ctx):
    got = ctx.sql(
        "SELECT g, COUNT(*) FILTER (WHERE v > 20) OVER (PARTITION BY g) "
        "AS big FROM w"
    )
    f = ctx._frame
    want = f.assign(big=(f["v"] > 20)).groupby("g", dropna=False)[
        "big"
    ].transform("sum")
    assert list(got["big"].astype(int)) == list(want.astype(int))


def test_window_expression_around_call(ctx):
    got = ctx.sql(
        "SELECT v, 100 * v / SUM(v) OVER () AS pct FROM w"
    )
    tot = ctx._frame["v"].sum()
    np.testing.assert_allclose(
        got["pct"].astype(float),
        100 * ctx._frame["v"] / tot,
        rtol=1e-9,
    )


def test_window_dedup_identical_specs(ctx):
    from spark_druid_olap_tpu.sql.parser import parse_sql
    from spark_druid_olap_tpu.plan import logical as L

    plan, _, _ = parse_sql(
        "SELECT v - AVG(v) OVER (PARTITION BY g) AS c1, "
        "AVG(v) OVER (PARTITION BY g) AS c2 FROM w"
    )
    win = plan
    while not isinstance(win, L.Window):
        win = win.children()[0]
    assert len(win.wins) == 1  # the identical spec computed once


def test_window_rejections(ctx):
    with pytest.raises(ParseError, match="not allowed in WHERE"):
        ctx.sql(
            "SELECT v FROM w WHERE ROW_NUMBER() OVER (ORDER BY v) < 5"
        )
    with pytest.raises(ParseError, match="not allowed in HAVING"):
        ctx.sql(
            "SELECT g, sum(v) FROM w GROUP BY g "
            "HAVING RANK() OVER (ORDER BY sum(v)) < 2"
        )
    with pytest.raises(ParseError, match="requires an OVER clause"):
        ctx.sql("SELECT ROW_NUMBER() FROM w")
    with pytest.raises(ParseError, match="requires ORDER BY"):
        ctx.sql("SELECT RANK() OVER (PARTITION BY g) FROM w")
    with pytest.raises(ParseError, match="inside aggregate"):
        ctx.sql("SELECT sum(ROW_NUMBER() OVER (ORDER BY v)) FROM w")
    with pytest.raises(ParseError, match="nested window"):
        ctx.sql(
            "SELECT RANK() OVER (ORDER BY SUM(v) OVER ()) FROM w"
        )
    with pytest.raises(ParseError, match="RANGE frames unsupported"):
        ctx.sql(
            "SELECT SUM(v) OVER (ORDER BY v RANGE BETWEEN 1 PRECEDING "
            "AND CURRENT ROW) FROM w"
        )
    with pytest.raises(ParseError, match="DISTINCT aggregates"):
        ctx.sql("SELECT SUM(DISTINCT v) OVER () FROM w")
    with pytest.raises(ParseError, match="SELECT alias"):
        ctx.sql("SELECT v FROM w ORDER BY ROW_NUMBER() OVER (ORDER BY v)")


def test_over_stays_usable_as_identifier(ctx):
    """OVER/PARTITION/ROWS are contextual words, not reserved keywords."""
    c = sd.TPUOlapContext()
    c.register_table(
        "q",
        {
            "over": np.array(["u", "u", "w"], dtype=object),
            "rows": np.array([1.0, 2.0, 3.0], dtype=np.float64),
        },
        dimensions=["over"],
        metrics=["rows"],
    )
    got = c.sql('SELECT over, sum(rows) AS s FROM q GROUP BY over')
    assert sorted(got["s"].astype(float)) == [3.0, 3.0]


def test_window_reports_fallback_executor(ctx):
    ctx.sql("SELECT v, ROW_NUMBER() OVER (ORDER BY v) AS rn FROM w")
    assert ctx.last_metrics.executor == "fallback"


def test_window_alias_shadowing_source_column(ctx):
    """A SELECT alias that shadows a source column must not corrupt later
    items reading the original (review-confirmed wrong-answer)."""
    c = sd.TPUOlapContext()
    c.register_table(
        "sh", {"v": np.array([1.0, 2.0, 3.0])}, metrics=["v"]
    )
    got = c.sql(
        "SELECT v + 1 AS v, v AS orig, "
        "ROW_NUMBER() OVER (ORDER BY v) AS rn FROM sh"
    )
    assert list(got["v"].astype(float)) == [2.0, 3.0, 4.0]
    assert list(got["orig"].astype(float)) == [1.0, 2.0, 3.0]


def test_window_query_with_scalar_subquery(ctx):
    """Subqueries elsewhere in the SELECT list coexist with windows
    (review-confirmed crash)."""
    c = sd.TPUOlapContext()
    c.register_table(
        "m", {"v": np.array([1.0, 5.0, 3.0])}, metrics=["v"]
    )
    c.register_table(
        "s", {"x": np.array([10.0, 20.0])}, metrics=["x"]
    )
    got = c.sql(
        "SELECT v, (SELECT max(x) FROM s) AS mx, "
        "ROW_NUMBER() OVER (ORDER BY v) AS rn FROM m"
    )
    assert (got["mx"].astype(float) == 20.0).all()
    assert sorted(got["rn"]) == [1, 2, 3]
    got2 = c.sql(
        "SELECT v, ROW_NUMBER() OVER (ORDER BY v) AS rn FROM m "
        "WHERE v IN (SELECT x / 10 FROM s)"
    )
    assert list(got2["v"].astype(float)) == [1.0]


def test_window_partition_by_aliased_group_key(ctx):
    """PARTITION BY g when GROUP BY g is SELECTed as `g AS grp`: the
    window spec must resolve to the aggregated frame's output name
    (review-confirmed KeyError)."""
    got = ctx.sql(
        "SELECT g AS grp, s, sum(v) AS sv, "
        "RANK() OVER (PARTITION BY g ORDER BY sum(v) DESC) AS r "
        "FROM w GROUP BY g, s"
    )
    f = ctx._frame
    want = (
        f.groupby(["g", "s"], dropna=False)["v"].sum().reset_index(name="sv")
    )
    want["r"] = want.groupby("g", dropna=False)["sv"].rank(
        method="min", ascending=False
    ).astype(int)
    merged = got.merge(
        want, left_on=["grp", "s"], right_on=["g", "s"], how="left"
    )
    assert (merged["r_x"] == merged["r_y"]).all()
    # expression group keys resolve the same way
    got2 = ctx.sql(
        "SELECT length(s) AS ls, sum(v) AS sv, "
        "RANK() OVER (PARTITION BY length(s) ORDER BY sum(v)) AS r "
        "FROM w GROUP BY length(s)"
    )
    assert len(got2) >= 1 and (got2["r"] == 1).all()


def test_window_in_setop_order_by_rejected(ctx):
    with pytest.raises(ParseError, match="output columns"):
        ctx.sql(
            "SELECT v FROM w UNION SELECT v FROM w "
            "ORDER BY ROW_NUMBER() OVER (ORDER BY v)"
        )


def test_window_over_ungrouped_column_rejected(ctx):
    with pytest.raises(ParseError, match="neither aggregated nor grouped"):
        ctx.sql(
            "SELECT g, SUM(v) OVER (PARTITION BY g) AS s FROM w GROUP BY g"
        )
    # ...but a window over a SELECT alias of an aggregate is fine
    got = ctx.sql(
        "SELECT g, sum(v) AS sv, RANK() OVER (ORDER BY sv) AS r "
        "FROM w GROUP BY g"
    )
    assert len(got) == 4


def _window_oracle(f, fn, partition, order_col, asc, frame, arg="v"):
    """Independent pandas implementation of one window column (nulls-last
    ordering, peer-inclusive default frames) for the fuzz differential."""
    out = pd.Series([None] * len(f), index=f.index, dtype=object)
    groups = (
        f.groupby(partition, dropna=False) if partition else [((), f)]
    )
    for _, gdf in groups:
        if order_col:
            o = gdf.sort_values(
                order_col, ascending=asc, kind="stable", na_position="last"
            )
        else:
            o = gdf
        vals = o[arg].to_numpy() if arg else None
        m = len(o)
        # peer groups on the order key (nulls are mutual peers at the end)
        if order_col:
            kv = o[order_col].fillna(np.inf if asc else -np.inf).to_numpy()
            peer_end = np.empty(m, dtype=int)
            i = 0
            while i < m:
                j = i
                while j + 1 < m and kv[j + 1] == kv[i]:
                    j += 1
                peer_end[i : j + 1] = j
                i = j + 1
        else:
            peer_end = np.full(m, m - 1)
        for i, idx in enumerate(o.index):
            if fn == "row_number":
                out[idx] = i + 1
                continue
            if fn == "rank":
                s = i
                while s > 0 and peer_end[s - 1] == peer_end[i]:
                    s -= 1
                out[idx] = s + 1
                continue
            if frame is not None:
                lo, hi = frame
                lo_i = 0 if lo is None else max(0, i + lo)
                hi_i = m - 1 if hi is None else min(m - 1, i + hi)
            elif order_col:
                lo_i, hi_i = 0, int(peer_end[i])
            else:
                lo_i, hi_i = 0, m - 1
            if lo_i > hi_i:
                out[idx] = 0 if fn == "count" else None
                continue
            w = vals[lo_i : hi_i + 1]
            w = w[~pd.isna(w)]
            if fn == "count":
                out[idx] = len(w)
            elif len(w) == 0:
                out[idx] = None
            elif fn == "sum":
                out[idx] = float(w.sum())
            elif fn == "min":
                out[idx] = float(w.min())
            elif fn == "max":
                out[idx] = float(w.max())
    return out


@pytest.mark.parametrize("seed", [4, 12, 23, 35, 47, 58])
def test_fuzz_windows_vs_oracle(ctx, seed):
    """Seeded random window shapes (fn x partition x order/desc x frame)
    against the independent oracle above."""
    rng = np.random.default_rng(seed)
    f = ctx._frame
    for _ in range(6):
        fn = rng.choice(["row_number", "rank", "sum", "count", "min", "max"])
        partition = list(
            rng.choice(["g", "s"], size=rng.integers(0, 3), replace=False)
        )
        has_order = fn in ("row_number", "rank") or rng.random() < 0.7
        asc = bool(rng.random() < 0.5)
        frame = None
        if fn not in ("row_number", "rank") and has_order and rng.random() < 0.4:
            lo = -int(rng.integers(0, 4))
            hi = int(rng.integers(0, 4))
            frame = (lo, hi)
        over = []
        if partition:
            over.append("PARTITION BY " + ", ".join(partition))
        if has_order:
            over.append("ORDER BY v" + ("" if asc else " DESC"))
        if frame is not None:
            def b(x, side):
                if x == 0:
                    return "CURRENT ROW"
                return f"{abs(x)} {'PRECEDING' if x < 0 else 'FOLLOWING'}"
            over.append(
                f"ROWS BETWEEN {b(frame[0], 0)} AND {b(frame[1], 1)}"
            )
        call = (
            f"{fn}()" if fn in ("row_number", "rank") else f"{fn}(v)"
        )
        q = (
            f"SELECT g, s, v, {call} OVER ({' '.join(over)}) AS w FROM w"
        )
        got = ctx.sql(q)
        want = _window_oracle(
            f, fn, partition, "v" if has_order else None, asc, frame
        )
        for idx in f.index:
            a, b2 = got["w"].iloc[idx], want.iloc[idx]
            if pd.isna(a) and (b2 is None or pd.isna(b2)):
                continue
            assert not pd.isna(a) and b2 is not None, (q, idx, a, b2)
            assert abs(float(a) - float(b2)) < 1e-6, (q, idx, a, b2)


def test_percent_rank_cume_dist_nth_value(ctx):
    c = sd.TPUOlapContext()
    c.register_table(
        "pr", {"v": np.array([1.0, 2.0, 2.0, 4.0])}, metrics=["v"]
    )
    got = c.sql(
        "SELECT v, PERCENT_RANK() OVER (ORDER BY v) AS pr, "
        "CUME_DIST() OVER (ORDER BY v) AS cd, "
        "NTH_VALUE(v, 2) OVER (ORDER BY v ROWS BETWEEN UNBOUNDED "
        "PRECEDING AND UNBOUNDED FOLLOWING) AS n2, "
        "NTH_VALUE(v, 9) OVER (ORDER BY v ROWS BETWEEN UNBOUNDED "
        "PRECEDING AND UNBOUNDED FOLLOWING) AS n9 FROM pr"
    )
    np.testing.assert_allclose(
        sorted(got["pr"].astype(float)), [0.0, 1 / 3, 1 / 3, 1.0]
    )
    np.testing.assert_allclose(
        sorted(got["cd"].astype(float)), [0.25, 0.75, 0.75, 1.0]
    )
    assert (got["n2"].astype(float) == 2.0).all()
    assert got["n9"].isna().all()  # frame shorter than 9 rows -> NULL
    with pytest.raises(ParseError, match="requires ORDER BY"):
        c.sql("SELECT PERCENT_RANK() OVER () FROM pr")
    with pytest.raises(ParseError, match="positive integer"):
        c.sql("SELECT NTH_VALUE(v, 0) OVER (ORDER BY v) FROM pr")


def test_device_assist_window_over_aggregate():
    """A window over a device-eligible GROUP BY base above the assist
    threshold runs the aggregate on the engine (executor device+fallback)
    and matches the float64 oracle (integer values: f32-exact sums)."""
    import numpy as np
    import pandas as pd

    import spark_druid_olap_tpu as sd
    from spark_druid_olap_tpu.config import SessionConfig

    cfg = SessionConfig(device_assist_min_rows=1000)
    c = sd.TPUOlapContext(cfg)
    rng = np.random.default_rng(4)
    n = 30_000
    f = pd.DataFrame({
        "g": rng.choice(["a", "b", "c", "d"], n),
        "s": rng.choice(["x", "y", "z"], n),
        "v": rng.integers(0, 100, n).astype(np.float64),
    })
    c.register_table("wbig", f)
    got = c.sql(
        "SELECT g, s, sum(v) AS sv, "
        "RANK() OVER (PARTITION BY g ORDER BY sum(v) DESC) AS r "
        "FROM wbig GROUP BY g, s"
    )
    assert c.last_metrics.executor == "device+fallback"
    want = f.groupby(["g", "s"], as_index=False)["v"].sum()
    want["r"] = want.groupby("g")["v"].rank(
        method="min", ascending=False
    ).astype(int)
    m = got.merge(want, on=["g", "s"])
    assert len(m) == len(want)
    np.testing.assert_array_equal(
        m["sv"].astype(np.int64), m["v"].astype(np.int64)
    )
    np.testing.assert_array_equal(
        m["r_x"].astype(int), m["r_y"].astype(int)
    )

    # below the threshold: pure host fallback, still correct
    cfg2 = SessionConfig()  # default threshold far above 30k rows
    c2 = sd.TPUOlapContext(cfg2)
    c2.register_table("wbig", f)
    got2 = c2.sql(
        "SELECT g, s, sum(v) AS sv, "
        "RANK() OVER (PARTITION BY g ORDER BY sum(v) DESC) AS r "
        "FROM wbig GROUP BY g, s"
    )
    assert c2.last_metrics.executor == "fallback"
    m2 = got.merge(got2, on=["g", "s"])
    np.testing.assert_array_equal(
        m2["r_x"].astype(int), m2["r_y"].astype(int)
    )
