"""Failure detection / idempotent re-dispatch (SURVEY.md §5 failure row).

The reference delegates retry to Spark task re-execution of a DruidRDD
partition — read-only queries make retry unconditionally safe.  The engine
mirrors that: a RuntimeError out of the device path evicts the query's
cached programs + resident columns and re-dispatches exactly once; static
planning errors propagate immediately."""

import numpy as np
import pytest

from spark_druid_olap_tpu.catalog.segment import build_datasource
from spark_druid_olap_tpu.exec.engine import Engine, _query_key
from spark_druid_olap_tpu.exec.lowering import groupby_with_time_granularity
from spark_druid_olap_tpu.models.aggregations import Count, DoubleSum
from spark_druid_olap_tpu.models.dimensions import DimensionSpec
from spark_druid_olap_tpu.models.query import GroupByQuery


@pytest.fixture(scope="module")
def ds():
    n = 10_000
    rng = np.random.default_rng(9)
    return build_datasource(
        "r",
        {
            "d": rng.integers(0, 8, n).astype(np.int64),
            "v": rng.random(n).astype(np.float32),
        },
        dimension_cols=["d"],
        metric_cols=["v"],
    )


def _q():
    return GroupByQuery(
        datasource="r",
        dimensions=(DimensionSpec("d"),),
        aggregations=(DoubleSum("s", "v"), Count("n")),
    )


def _oracle(ds):
    import pandas as pd

    seg = ds.segments[0]
    d = ds.dicts["d"].decode(np.asarray(seg.dims["d"])[seg.valid])
    v = np.asarray(seg.metrics["v"], np.float64)[seg.valid]
    return (
        pd.DataFrame({"d": d, "v": v})
        .groupby("d", as_index=False)
        .agg(s=("v", "sum"), n=("v", "count"))
    )


def test_transient_failure_retries_once(ds):
    eng = Engine()
    q = groupby_with_time_granularity(_q())
    lowering = eng._lowering_for(q, ds)
    strategy = eng._resolve_strategy(lowering.num_groups)
    calls = {"n": 0}

    def poisoned(cols_list):
        calls["n"] += 1
        raise RuntimeError("injected transient device failure")

    eng._query_fn_cache[_query_key(q, ds) + ("fused", strategy)] = poisoned
    got = eng.execute(_q(), ds).sort_values("d").reset_index(drop=True)
    want = _oracle(ds).sort_values("d").reset_index(drop=True)
    assert calls["n"] >= 1  # the poisoned program actually ran
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)


def test_retry_evicts_transformed_query_identity(ds):
    """A granularity GroupBy is rewritten (adds a __time dimension) before
    caching; the retry must evict under the TRANSFORMED identity or the
    poisoned program survives and the retry fails identically."""
    import dataclasses

    n = 4_096
    rng = np.random.default_rng(3)
    tds = build_datasource(
        "rt",
        {
            "d": rng.integers(0, 4, n).astype(np.int64),
            "v": rng.random(n).astype(np.float32),
            "t": (
                np.int64(1_600_000_000_000)
                + rng.integers(0, 3, n).astype(np.int64) * 86_400_000
            ),
        },
        dimension_cols=["d"],
        metric_cols=["v"],
        time_col="t",
    )
    raw = GroupByQuery(
        datasource="rt",
        dimensions=(DimensionSpec("d"),),
        aggregations=(Count("n"),),
        granularity="day",
    )
    eng = Engine()
    qt = groupby_with_time_granularity(raw)
    assert qt is not raw  # the transform actually rewrote it
    lowering = eng._lowering_for(qt, tds)
    strategy = eng._resolve_strategy(lowering.num_groups)

    def poisoned(cols_list):
        raise RuntimeError("injected transient device failure")

    eng._query_fn_cache[_query_key(qt, tds) + ("fused", strategy)] = poisoned
    got = eng.execute(raw, tds)
    assert int(got["n"].sum()) == n


def test_persistent_failure_surfaces(ds):
    eng = Engine()
    q = groupby_with_time_granularity(_q())

    def always_fail(self, q, ds, lowering, **kwargs):
        def fn(cols_list):
            raise RuntimeError("device permanently unreachable")

        return fn

    eng._segment_program = always_fail.__get__(eng)
    with pytest.raises(RuntimeError, match="permanently unreachable"):
        eng.execute(_q(), ds)


def test_static_errors_do_not_retry(ds):
    eng = Engine()
    calls = {"n": 0}
    orig = Engine._execute_groupby_once

    def counting(self, q, ds):
        calls["n"] += 1
        raise ValueError("static planning error")

    eng._execute_groupby_once = counting.__get__(eng)
    with pytest.raises(ValueError):
        eng.execute(_q(), ds)
    assert calls["n"] == 1  # no second dispatch for non-transient errors
