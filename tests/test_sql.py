"""SQL end-to-end: parse -> plan -> execute -> parity vs pandas oracle.

The analog of the reference's `DruidRewritesTest` + `TPCHTest` suites
(SURVEY.md §4 `[U]`): run SQL, assert the rewrite produced the expected query
type (the "plan contains DruidQuery" assertion), and check results against an
un-accelerated oracle on the same data."""

import numpy as np
import pandas as pd
import pytest

from spark_druid_olap_tpu import TPUOlapContext
from spark_druid_olap_tpu.models.query import (
    GroupByQuery,
    ScanQuery,
    TimeseriesQuery,
    TopNQuery,
)
from spark_druid_olap_tpu.utils import datagen


@pytest.fixture(scope="module")
def ctx(lineitem_cols, ssb_cols):
    c = TPUOlapContext()
    c.register_table(
        "lineitem",
        lineitem_cols,
        dimensions=datagen.LINEITEM_DIMS,
        metrics=datagen.LINEITEM_METRICS,
        time_column="l_shipdate",
        rows_per_segment=16384,
    )
    c.register_table(
        "lineorder",
        ssb_cols,
        dimensions=datagen.SSB_DIMS,
        metrics=datagen.SSB_METRICS,
        time_column="lo_orderdate",
        rows_per_segment=16384,
    )
    return c


def test_tpch_q1_sql(ctx, lineitem_cols):
    """BASELINE config #1 via the SQL surface."""
    got = ctx.sql(
        """
        SELECT l_returnflag, l_linestatus,
               sum(l_quantity) AS sum_qty,
               sum(l_extendedprice) AS sum_base_price,
               sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
               sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
               avg(l_quantity) AS avg_qty,
               avg(l_extendedprice) AS avg_price,
               avg(l_discount) AS avg_disc,
               count(*) AS count_order
        FROM lineitem
        WHERE l_shipdate <= DATE '1998-09-02'
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus
        """
    )
    c = lineitem_cols
    cutoff = int(np.datetime64("1998-09-02").astype("datetime64[ms]").astype(np.int64))
    m = np.asarray(c["l_shipdate"]) <= cutoff
    df = pd.DataFrame({k: np.asarray(v)[m] for k, v in c.items()})
    df["dp"] = df.l_extendedprice.astype(np.float64) * (1 - df.l_discount)
    df["ch"] = df.dp * (1 + df.l_tax)
    want = (
        df.groupby(["l_returnflag", "l_linestatus"], sort=True)
        .agg(
            sum_qty=("l_quantity", "sum"),
            sum_disc_price=("dp", "sum"),
            sum_charge=("ch", "sum"),
            avg_qty=("l_quantity", "mean"),
            count_order=("l_quantity", "size"),
        )
        .reset_index()
    )
    assert list(got.columns) == [
        "l_returnflag", "l_linestatus", "sum_qty", "sum_base_price",
        "sum_disc_price", "sum_charge", "avg_qty", "avg_price", "avg_disc",
        "count_order",
    ]
    np.testing.assert_array_equal(got.count_order, want.count_order)
    np.testing.assert_allclose(got.sum_qty, want.sum_qty, rtol=2e-5)
    np.testing.assert_allclose(got.sum_disc_price, want.sum_disc_price, rtol=2e-5)
    np.testing.assert_allclose(got.sum_charge, want.sum_charge, rtol=2e-5)
    np.testing.assert_allclose(got.avg_qty, want.avg_qty, rtol=2e-5)


def test_rewrite_types(ctx):
    """The 'plan contains DruidQuery' analog: most specific spec wins."""
    rw = ctx.plan_sql(
        "SELECT date_trunc('hour', l_shipdate) h, count(*) n "
        "FROM lineitem GROUP BY date_trunc('hour', l_shipdate)"
    )
    assert isinstance(rw.query, TimeseriesQuery)

    rw = ctx.plan_sql(
        "SELECT l_returnflag, sum(l_quantity) q FROM lineitem "
        "GROUP BY l_returnflag ORDER BY q DESC LIMIT 2"
    )
    assert isinstance(rw.query, TopNQuery)

    rw = ctx.plan_sql(
        "SELECT l_returnflag, l_linestatus, count(*) n FROM lineitem "
        "GROUP BY l_returnflag, l_linestatus"
    )
    assert isinstance(rw.query, GroupByQuery)

    rw = ctx.plan_sql("SELECT l_returnflag FROM lineitem WHERE l_quantity > 49")
    assert isinstance(rw.query, ScanQuery)


def test_interval_extraction(ctx):
    rw = ctx.plan_sql(
        "SELECT count(*) n FROM lineitem "
        "WHERE l_shipdate >= DATE '1994-01-01' AND l_shipdate < DATE '1995-01-01'"
    )
    assert rw.query.intervals, "time predicates must narrow the interval"
    (lo, hi), = rw.query.intervals
    assert np.datetime64(int(lo), "ms") == np.datetime64("1994-01-01")
    assert np.datetime64(int(hi), "ms") == np.datetime64("1995-01-01")
    assert rw.query.filter is None, "time bounds must not duplicate as filters"


def test_having_and_alias_order(ctx, lineitem_cols):
    got = ctx.sql(
        "SELECT l_returnflag f, count(*) n FROM lineitem "
        "GROUP BY l_returnflag HAVING count(*) > 1000 ORDER BY n DESC"
    )
    c = pd.Series(np.asarray(lineitem_cols["l_returnflag"], dtype=object))
    want = c.value_counts()
    want = want[want > 1000].sort_values(ascending=False)
    assert list(got.f) == list(want.index)
    np.testing.assert_array_equal(got.n, want.values)


def test_filtered_agg_and_case(ctx, lineitem_cols):
    got = ctx.sql(
        "SELECT l_returnflag f, "
        "count(*) FILTER (WHERE l_linestatus = 'O') AS open_n, "
        "sum(CASE WHEN l_discount > 0.05 THEN l_quantity ELSE 0 END) AS disc_qty "
        "FROM lineitem GROUP BY l_returnflag ORDER BY f"
    )
    df = pd.DataFrame(
        {
            "f": np.asarray(lineitem_cols["l_returnflag"], dtype=object),
            "s": np.asarray(lineitem_cols["l_linestatus"], dtype=object),
            "d": np.asarray(lineitem_cols["l_discount"], np.float64),
            "q": np.asarray(lineitem_cols["l_quantity"], np.float64),
        }
    )
    want_open = df[df.s == "O"].groupby("f").size()
    want_disc = df.assign(x=np.where(df.d > 0.05, df.q, 0)).groupby("f").x.sum()
    np.testing.assert_array_equal(got.open_n, want_open.values)
    np.testing.assert_allclose(got.disc_qty, want_disc.values, rtol=2e-5)


def test_approx_count_distinct(ctx, lineitem_cols):
    got = ctx.sql(
        "SELECT approx_count_distinct(l_orderkey) u FROM lineitem"
    )
    truth = len(np.unique(np.asarray(lineitem_cols["l_orderkey"])))
    assert abs(int(got.u[0]) - truth) / truth < 0.1


def test_cube(ctx, ssb_cols):
    got = ctx.sql(
        "SELECT c_region, s_region, sum(lo_revenue) rev "
        "FROM lineorder GROUP BY CUBE(c_region, s_region)"
    )
    df = pd.DataFrame(
        {
            "c": np.asarray(ssb_cols["c_region"], dtype=object),
            "s": np.asarray(ssb_cols["s_region"], dtype=object),
            "r": np.asarray(ssb_cols["lo_revenue"], np.float64),
        }
    )
    # 4 grouping sets: (), (c), (s), (c,s)
    n_c = df.c.nunique()
    n_s = df.s.nunique()
    assert len(got) == 1 + n_c + n_s + n_c * n_s
    total = got[got.__grouping_id == 3].rev.iloc[0]
    np.testing.assert_allclose(total, df.r.sum(), rtol=2e-5)
    full = got[got.__grouping_id == 0]
    want = df.groupby(["c", "s"]).r.sum().reset_index()
    np.testing.assert_allclose(
        full.sort_values(["c_region", "s_region"]).rev.values,
        want.sort_values(["c", "s"]).r.values,
        rtol=2e-5,
    )


def test_ssb_q1_1(ctx, ssb_cols):
    """SSB Q1.1 (BASELINE config #2 shape, flat form)."""
    got = ctx.sql(
        "SELECT sum(lo_extendedprice * lo_discount / 100) AS revenue "
        "FROM lineorder WHERE d_year = 1993 "
        "AND lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25"
    )
    y = np.asarray(ssb_cols["d_year"])
    d = np.asarray(ssb_cols["lo_discount"], np.float64)
    q = np.asarray(ssb_cols["lo_quantity"], np.float64)
    p = np.asarray(ssb_cols["lo_extendedprice"], np.float64)
    m = (y == 1993) & (d >= 1) & (d <= 3) & (q < 25)
    np.testing.assert_allclose(got.revenue[0], (p[m] * d[m] / 100).sum(), rtol=2e-5)


def test_global_agg_empty_match(ctx):
    """SQL: a global aggregate over zero matching rows yields ONE row with
    COUNT=0 and NULL sums/extrema — never an empty frame."""
    got = ctx.sql(
        "SELECT count(*) n, sum(lo_revenue) s, min(lo_quantity) mn "
        "FROM lineorder WHERE d_year = 2050"
    )
    assert len(got) == 1
    assert int(got.n[0]) == 0
    assert np.isnan(got.s[0]) and np.isnan(got.mn[0])


def test_numeric_dim_dictionary_tightness(ctx, ssb_cols):
    """Integer dims are rank-encoded against their actual value domain, so
    group cardinality stays tight (d_year: 7 codes, not max-value codes)."""
    ds = ctx.catalog.get("lineorder")
    assert ds.cardinality("d_year") == len(np.unique(ssb_cols["d_year"]))
    got = ctx.sql(
        "SELECT d_yearmonthnum ym, count(*) n FROM lineorder "
        "WHERE d_yearmonthnum >= 199401 AND d_yearmonthnum <= 199403 "
        "GROUP BY d_yearmonthnum ORDER BY ym"
    )
    ym = np.asarray(ssb_cols["d_yearmonthnum"])
    m = (ym >= 199401) & (ym <= 199403)
    want = pd.Series(ym[m]).value_counts().sort_index()
    assert list(got.ym) == list(want.index)
    np.testing.assert_array_equal(got.n, want.values)


def test_explain(ctx):
    out = ctx.explain(
        "SELECT l_returnflag, sum(l_quantity) FROM lineitem GROUP BY l_returnflag"
    )
    assert "Logical Plan" in out
    assert "groupBy" in out
    assert "TPUAggregateScan" in out


def test_scan_query(ctx, lineitem_cols):
    got = ctx.sql(
        "SELECT l_returnflag, l_quantity FROM lineitem "
        "WHERE l_quantity >= 50 LIMIT 37"
    )
    assert list(got.columns) == ["l_returnflag", "l_quantity"]
    assert len(got) == 37
    assert (got.l_quantity >= 50).all()


def test_dataframe_builder(ctx, lineitem_cols):
    from spark_druid_olap_tpu.plan.expr import col

    got = (
        ctx.table("lineitem")
        .filter(col("l_linestatus").eq("F"))
        .group_by("l_returnflag")
        .agg(n=("count", None), qty=("sum", "l_quantity"))
        .order_by("l_returnflag")
        .collect()
    )
    df = pd.DataFrame(
        {
            "f": np.asarray(lineitem_cols["l_returnflag"], dtype=object),
            "s": np.asarray(lineitem_cols["l_linestatus"], dtype=object),
            "q": np.asarray(lineitem_cols["l_quantity"], np.float64),
        }
    )
    want = df[df.s == "F"].groupby("f").agg(n=("q", "size"), qty=("q", "sum"))
    np.testing.assert_array_equal(got.n, want.n.values)
    np.testing.assert_allclose(got.qty, want.qty.values, rtol=2e-5)


def test_scan_order_by_and_offset(ctx, lineitem_cols):
    """ORDER BY / OFFSET on a non-aggregate scan must be honored (they were
    silently dropped: unsorted rows under LIMIT are wrong rows)."""
    got = ctx.sql(
        "SELECT l_returnflag, l_extendedprice FROM lineitem "
        "ORDER BY l_extendedprice DESC LIMIT 5"
    )
    v = list(got["l_extendedprice"])
    assert v == sorted(v, reverse=True)
    import numpy as np

    top = np.sort(np.asarray(lineitem_cols["l_extendedprice"], np.float64))[
        -5:
    ][::-1]
    np.testing.assert_allclose(np.asarray(v, np.float64), top, rtol=1e-6)

    # OFFSET skips rows deterministically under an ordering
    nxt = ctx.sql(
        "SELECT l_extendedprice FROM lineitem "
        "ORDER BY l_extendedprice DESC LIMIT 3 OFFSET 2"
    )
    np.testing.assert_allclose(
        np.asarray(nxt["l_extendedprice"], np.float64), top[2:5], rtol=1e-6
    )

    # ascending with a string dimension sorts on decoded values
    asc = ctx.sql(
        "SELECT l_returnflag FROM lineitem ORDER BY l_returnflag LIMIT 4"
    )
    f = list(asc["l_returnflag"])
    assert f == sorted(f)


def test_scan_wire_order_roundtrip(ctx):
    from spark_druid_olap_tpu.models.wire import query_from_druid

    rw = ctx.plan_sql(
        "SELECT l_returnflag FROM lineitem ORDER BY l_returnflag LIMIT 4"
    )
    q2 = query_from_druid(rw.query.to_druid())
    assert q2 == rw.query
    # legacy `order` field decodes to time ordering
    legacy = dict(rw.query.to_druid())
    legacy.pop("orderBy")
    legacy["order"] = "descending"
    q3 = query_from_druid(legacy)
    assert q3.order_by[0].dimension == "__time"


def test_scan_order_by_computed_alias(ctx, lineitem_cols):
    """ORDER BY a SELECT alias of a computed projection sorts on the
    evaluated virtual column."""
    got = ctx.sql(
        "SELECT l_extendedprice * 2 AS p FROM lineitem "
        "ORDER BY p DESC LIMIT 4"
    )
    v = list(got["p"])
    assert v == sorted(v, reverse=True)
    import numpy as np

    top = np.sort(
        np.asarray(lineitem_cols["l_extendedprice"], np.float64) * 2
    )[-4:][::-1]
    np.testing.assert_allclose(np.asarray(v, np.float64), top, rtol=1e-6)


def test_scan_wire_bad_order_column_is_clean_error(ctx):
    from spark_druid_olap_tpu.models.wire import query_from_druid

    rw = ctx.plan_sql("SELECT l_returnflag FROM lineitem LIMIT 3")
    body = dict(rw.query.to_druid())
    body["orderBy"] = [{"columnName": "nope"}]
    q = query_from_druid(body)
    import pytest

    with pytest.raises(ValueError, match="unknown column"):
        ctx.engine.execute(q, ctx.catalog.get("lineitem"))


def test_grouping_function(ctx):
    """SQL GROUPING(col): 1 on rolled-away rows, 0 elsewhere — desugared
    to a bit test over __grouping_id; works on device AND fallback, in
    SELECT and HAVING; plain GROUP BY yields constant 0."""
    got = ctx.sql(
        "SELECT l_returnflag, l_linestatus, GROUPING(l_returnflag) AS gf, "
        "GROUPING(l_linestatus) AS gs, sum(l_quantity) AS q "
        "FROM lineitem GROUP BY CUBE (l_returnflag, l_linestatus)"
    )
    # rolled-away dimension <=> its GROUPING bit set
    for _, r in got.iterrows():
        assert (int(r["gf"]) == 1) == pd.isna(r["l_returnflag"])
        assert (int(r["gs"]) == 1) == pd.isna(r["l_linestatus"])
    # HAVING GROUPING: keep only the grand total
    tot = ctx.sql(
        "SELECT sum(l_quantity) AS q, GROUPING(l_returnflag) AS gf "
        "FROM lineitem GROUP BY ROLLUP (l_returnflag) "
        "HAVING GROUPING(l_returnflag) = 1"
    )
    assert len(tot) == 1 and int(tot["gf"].iloc[0]) == 1
    plain = ctx.sql(
        "SELECT l_returnflag, GROUPING(l_returnflag) AS gf FROM lineitem "
        "GROUP BY l_returnflag"
    )
    assert (plain["gf"] == 0).all()
    from spark_druid_olap_tpu.sql.parser import ParseError

    with pytest.raises(ParseError, match="GROUP BY"):
        ctx.sql(
            "SELECT GROUPING(l_quantity) FROM lineitem "
            "GROUP BY l_returnflag"
        )


def test_grouping_in_order_by(ctx):
    """High-review finding: ORDER BY GROUPING(col) — the standard idiom
    for pushing subtotal rows last — substitutes like SELECT/HAVING."""
    got = ctx.sql(
        "SELECT l_returnflag, sum(l_quantity) AS q FROM lineitem "
        "GROUP BY ROLLUP (l_returnflag) ORDER BY GROUPING(l_returnflag), "
        "l_returnflag"
    )
    assert pd.isna(got["l_returnflag"].iloc[-1])  # grand total last
    assert not got["l_returnflag"].iloc[:-1].isna().any()
