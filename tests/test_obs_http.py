"""HTTP observability surface (ISSUE 4 satellites): X-Druid-Query-Id
echo + context.queryId passthrough, the trace ring endpoint (span trees
whose phase durations sum to ≈ total_ms), Prometheus exposition at
/status/metrics with monotonic counters, trace ring eviction, the
structured access log, and concurrent-query span-tree isolation."""

import json
import logging
import re
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.config import SessionConfig
from spark_druid_olap_tpu.server import OlapServer


def _make_ctx(**overrides):
    cfg = SessionConfig.load_calibrated()
    cfg.result_cache_entries = 0
    for k, v in overrides.items():
        setattr(cfg, k, v)
    ctx = sd.TPUOlapContext(cfg)
    rng = np.random.default_rng(5)
    n = 3_000
    ctx.register_table(
        "ev",
        {
            "city": rng.choice(
                np.array(["NY", "SF", "LA"], dtype=object), n
            ),
            "v": rng.random(n).astype(np.float32),
        },
        dimensions=["city"],
        metrics=["v"],
    )
    return ctx


@pytest.fixture()
def srv():
    ctx = _make_ctx()
    server = OlapServer(ctx, port=0).start()
    try:
        yield ctx, server
    finally:
        server.shutdown()


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=30
    ) as r:
        return r.status, r.read(), dict(r.headers)


def _get_json(port, path):
    code, body, headers = _get(port, path)
    return code, json.loads(body), headers


def _post(port, path, payload, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


_SQL = {"query": "SELECT city, sum(v) AS s FROM ev GROUP BY city"}


def _get_trace(port, qid, tries=200):
    """Fetch a trace, tolerating the benign registration race: the ring
    put happens a hair after the response bytes land (same shape as the
    admission-slot release in test_server_resilience)."""
    import time

    for _ in range(tries):
        code, body, _ = _get_json_allow_error(
            port, f"/druid/v2/trace/{qid}"
        )
        if code == 200:
            return body
        time.sleep(0.01)
    raise AssertionError(f"trace {qid!r} never appeared")


# ---------------------------------------------------------------------------
# query_id end-to-end
# ---------------------------------------------------------------------------


def test_context_query_id_passthrough_and_echo(srv):
    ctx, server = srv
    code, rows, headers = _post(
        server.port, "/druid/v2/sql",
        {**_SQL, "context": {"queryId": "dash-42"}},
    )
    assert code == 200
    assert headers["X-Druid-Query-Id"] == "dash-42"
    # the id reached the engine: last_metrics carries it
    assert ctx.last_metrics.query_id == "dash-42"


def test_generated_query_id_when_client_sets_none(srv):
    ctx, server = srv
    code, rows, h1 = _post(server.port, "/druid/v2/sql", _SQL)
    assert code == 200
    qid1 = h1["X-Druid-Query-Id"]
    assert qid1
    code, rows, h2 = _post(server.port, "/druid/v2/sql", _SQL)
    assert h2["X-Druid-Query-Id"] != qid1  # fresh id per request


def test_native_query_id_echo_and_error_responses_carry_id(srv):
    ctx, server = srv
    native = {
        "queryType": "groupBy",
        "dataSource": "ev",
        "granularity": "all",
        "dimensions": [{"type": "default", "dimension": "city"}],
        "aggregations": [{"type": "count", "name": "n"}],
        "context": {"queryId": "native-7"},
    }
    code, body, headers = _post(server.port, "/druid/v2", native)
    assert code == 200
    assert headers["X-Druid-Query-Id"] == "native-7"
    # a client error still echoes the id (Druid parity: errors correlate)
    bad = {**native, "dataSource": "nope", "context": {"queryId": "bad-1"}}
    code, body, headers = _post(server.port, "/druid/v2", bad)
    assert code == 400
    assert headers["X-Druid-Query-Id"] == "bad-1"


# ---------------------------------------------------------------------------
# Trace endpoint + acceptance: phase durations sum ≈ total_ms
# ---------------------------------------------------------------------------


def test_trace_endpoint_returns_span_tree_with_phase_sums(srv):
    ctx, server = srv
    code, rows, headers = _post(
        server.port, "/druid/v2/sql",
        {**_SQL, "context": {"queryId": "traced-1"}},
    )
    assert code == 200
    trace = _get_trace(server.port, "traced-1")
    assert trace["query_id"] == "traced-1"
    root = trace["spans"]
    assert root["name"] == "query"
    total = trace["total_ms"]
    assert total > 0
    names = [c["name"] for c in root["children"]]
    assert "admission" in names and "plan" in names and "execute" in names
    # contiguous top-level phases: their durations sum to ≈ total_ms
    # (never more; the gaps between spans are microseconds of glue)
    phase_sum = sum(c["duration_ms"] for c in root["children"])
    assert phase_sum <= total * 1.01 + 0.5
    assert phase_sum >= total * 0.5
    # the execute phase contains the engine spans
    execute = next(c for c in root["children"] if c["name"] == "execute")
    inner = {c["name"] for c in execute.get("children", ())}
    assert "segment_dispatch" in inner or "lower" in inner


def test_trace_endpoint_404_for_unknown_id(srv):
    ctx, server = srv
    code, body, _ = _get_json_allow_error(server.port, "/druid/v2/trace/nope")
    assert code == 404
    assert body["errorClass"] == "NotFound"


def _get_json_allow_error(port, path):
    try:
        code, body, _ = _get(port, path)
        return code, json.loads(body), _
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_trace_ring_eviction_over_http():
    ctx = _make_ctx(trace_ring_capacity=2)
    server = OlapServer(ctx, port=0).start()
    try:
        for qid in ("r1", "r2", "r3"):
            code, _, _ = _post(
                server.port, "/druid/v2/sql",
                {**_SQL, "context": {"queryId": qid}},
            )
            assert code == 200
        # wait for the LAST trace to register (ring put trails the
        # response bytes by a hair), then r1 must be the evicted one
        for qid in ("r2", "r3"):
            assert _get_trace(server.port, qid)["query_id"] == qid
        code, _, _ = _get_json_allow_error(
            server.port, "/druid/v2/trace/r1"
        )
        assert code == 404  # evicted (capacity 2, FIFO)
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^}]*\})?) (\S+)$")


def _scrape(port):
    code, body, headers = _get(port, "/status/metrics")
    assert code == 200
    assert headers["Content-Type"].startswith("text/plain")
    text = body.decode()
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        samples[m.group(1)] = float(m.group(2))
    return text, samples


def test_status_metrics_parses_and_counters_never_decrease(srv):
    ctx, server = srv
    _post(server.port, "/druid/v2/sql", _SQL)
    text1, s1 = _scrape(server.port)
    assert any(k.startswith("sdol_queries_total") for k in s1)
    assert "# TYPE sdol_queries_total counter" in text1
    assert "# TYPE sdol_query_phase_ms histogram" in text1
    for _ in range(3):
        assert _post(server.port, "/druid/v2/sql", _SQL)[0] == 200
    text2, s2 = _scrape(server.port)
    # monotonicity: every counter/histogram sample present in scrape 1
    # is >= in scrape 2 (gauges may move either way)
    for key, v1 in s1.items():
        name = key.split("{")[0]
        if name.endswith(("_total", "_bucket", "_count", "_sum")):
            assert s2.get(key, 0) >= v1, key
    # and the query counter visibly incremented
    qkey = next(
        k for k in s2
        if k.startswith("sdol_queries_total") and 'outcome="ok"' in k
        and 'executor="device"' in k and 'query_type="groupBy"' in k
    )
    assert s2[qkey] >= s1.get(qkey, 0) + 3
    # the http counter covers the serving surface itself
    assert any(k.startswith("sdol_http_requests_total") for k in s2)


def test_status_folds_registry_summary(srv):
    ctx, server = srv
    _post(server.port, "/druid/v2/sql", _SQL)
    code, st, _ = _get_json(server.port, "/status")
    assert code == 200
    metrics = st["metrics"]
    assert metrics["sdol_queries_total"]["type"] == "counter"
    phase = metrics["sdol_query_phase_ms"]
    assert phase["type"] == "histogram"
    total = phase["values"]["total"]
    assert total["count"] >= 1 and total["p50"] is not None


# ---------------------------------------------------------------------------
# Access log (ISSUE 4 satellite: structured DEBUG replaces the silence)
# ---------------------------------------------------------------------------


def test_access_log_structured_at_debug(srv, caplog):
    ctx, server = srv
    with caplog.at_level(
        logging.DEBUG, logger="spark_druid_olap_tpu.server"
    ):
        code, _, headers = _post(
            server.port, "/druid/v2/sql",
            {**_SQL, "context": {"queryId": "logged-1"}},
        )
        assert code == 200
    msgs = [r.getMessage() for r in caplog.records]
    access = [m for m in msgs if m.startswith("access ")]
    assert access, msgs
    line = next(m for m in access if "query_id=logged-1" in m)
    assert "method=POST" in line
    assert "path=/druid/v2/sql" in line
    assert "status=200" in line
    assert re.search(r"duration_ms=\d+\.\d+", line)


# ---------------------------------------------------------------------------
# Concurrency: span trees stay per-query under a hammer
# ---------------------------------------------------------------------------


def test_concurrent_query_span_trees_do_not_interleave(srv):
    """8 threads, unique queryIds: every trace must contain exactly its
    own query's phases (one admission, one plan, one execute) — a shared
    or leaked contextvar would double spans up or cross-file them."""
    ctx, server = srv
    results = {}
    lock = threading.Lock()

    def hit(i):
        qid = f"conc-{i}"
        r = _post(
            server.port, "/druid/v2/sql",
            {**_SQL, "context": {"queryId": qid}},
        )
        with lock:
            results[qid] = r

    threads = [threading.Thread(target=hit, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert len(results) == 8
    for qid, (code, rows, headers) in results.items():
        assert code == 200, (qid, rows)
        assert headers["X-Druid-Query-Id"] == qid
        trace = _get_trace(server.port, qid)
        assert trace["query_id"] == qid
        names = [c["name"] for c in trace["spans"]["children"]]
        # exactly one of each top-level phase: no cross-query bleed
        assert names.count("admission") == 1, (qid, names)
        assert names.count("plan") == 1, (qid, names)
        assert names.count("execute") == 1, (qid, names)
