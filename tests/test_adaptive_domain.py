"""Adaptive dictionary-domain compaction (exec/adaptive_exec.py).

The SSB q3/q4 shape: a huge combined dictionary domain where the filter
admits only a few codes per dimension.  These tests pin the compacted
execution to a float64 pandas oracle, the decline paths (no marginal
shrink -> sparse/scatter), sketch aggregates through the compact domain,
and the kept-set cache making repeats one-pass.
"""

import numpy as np
import pandas as pd
import pytest

from spark_druid_olap_tpu.catalog.segment import DimensionDict, build_datasource
from spark_druid_olap_tpu.exec.engine import Engine
from spark_druid_olap_tpu.exec.lowering import memo_key
from spark_druid_olap_tpu.models.aggregations import (
    Count,
    DoubleMax,
    DoubleMin,
    DoubleSum,
    HyperUnique,
)
from spark_druid_olap_tpu.models.dimensions import DimensionSpec
from spark_druid_olap_tpu.models.filters import Bound, InFilter, Selector
from spark_druid_olap_tpu.models.query import GroupByQuery


def _make_ds(n=60_000, da=400, db=400, seed=3, segs=3, name="ad"):
    """Marginally-shrinkable data: rows concentrate on a few codes per dim
    UNDER THE FILTER, while the combined domain is da*db >> 4096."""
    rng = np.random.default_rng(seed)
    a = rng.integers(0, da, size=n)
    b = rng.integers(0, db, size=n)
    cols = {
        "a": a,
        "b": b,
        "v": (rng.random(n) * 100).astype(np.float32),
        "k": rng.integers(0, 5000, size=n),
    }
    ds = build_datasource(
        name,
        cols,
        dimension_cols=["a", "b"],
        metric_cols=["v", "k"],
        rows_per_segment=n // segs,
        dicts={
            "a": DimensionDict(values=tuple(range(da))),
            "b": DimensionDict(values=tuple(range(db))),
        },
    )
    return ds, cols


def _oracle(cols, mask):
    df = pd.DataFrame(
        {k: np.asarray(v, dtype=np.float64) for k, v in cols.items()}
    )
    df = df[mask]
    g = df.groupby(["a", "b"], as_index=False).agg(
        n=("v", "count"), s=("v", "sum"), lo=("v", "min"), hi=("v", "max")
    )
    return g.sort_values(["a", "b"]).reset_index(drop=True)


def _norm(df):
    out = df.sort_values(["a", "b"]).reset_index(drop=True)
    return out.assign(
        a=out.a.astype(np.float64),
        b=out.b.astype(np.float64),
        n=out.n.astype(np.int64),
    )


def _query(filter=None, aggs=None):
    return GroupByQuery(
        datasource="ad",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=aggs
        or (
            Count("n"),
            DoubleSum("s", "v"),
            DoubleMin("lo", "v"),
            DoubleMax("hi", "v"),
        ),
        filter=filter,
    )


def test_adaptive_parity_and_kept_cache():
    ds, cols = _make_ds()
    keep_a = tuple(range(0, 12))
    keep_b = tuple(range(0, 9))
    q = _query(
        filter=InFilter("a", keep_a).and_(InFilter("b", keep_b))
        if hasattr(InFilter, "and_")
        else None
    )
    from spark_druid_olap_tpu.models.filters import And

    q = _query(filter=And((InFilter("a", keep_a), InFilter("b", keep_b))))
    eng = Engine(strategy="adaptive")
    got = _norm(eng.execute(q, ds))
    mask = np.isin(cols["a"], keep_a) & np.isin(cols["b"], keep_b)
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["a"], want["a"])
    np.testing.assert_array_equal(got["b"], want["b"])
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    np.testing.assert_allclose(got["lo"], want["lo"], rtol=1e-6)
    np.testing.assert_allclose(got["hi"], want["hi"], rtol=1e-6)
    assert eng.last_metrics.strategy == "adaptive"
    # kept sets cached; a repeat skips phase A and stays exact.  Memo
    # entries key segment-set-independently and measured ones carry the
    # scanned segment signature (ingest-tier contract: a delta append
    # must re-measure, a plain repeat must not)
    qkey = memo_key(q, ds)
    assert qkey in eng._adaptive_kept
    entry = eng._adaptive_kept[qkey]
    if entry[0] == "measured":
        _, seg_sig, kept = entry
        assert seg_sig == tuple(s.uid for s in ds.segments)
    else:
        assert entry[0] == "derived"
        kept = entry[1]
    assert len(kept[0]) <= len(keep_a) and len(kept[1]) <= len(keep_b)
    got2 = _norm(eng.execute(q, ds))
    pd.testing.assert_frame_equal(got, got2)


def test_adaptive_declines_without_shrink_falls_to_sparse():
    """Uniform data: marginals keep every code, compaction gains nothing —
    decline memo set, sparse path answers, results exact."""
    ds, cols = _make_ds()
    q = _query()
    eng = Engine(strategy="adaptive")
    got = _norm(eng.execute(q, ds))
    want = _oracle(cols, np.ones(len(cols["a"]), bool))
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    assert eng._adaptive_declined
    assert eng.last_metrics.strategy in ("sparse", "segment", "dense")


def test_adaptive_with_hll_sketch():
    """Sketch aggregates run through the compact domain (the sparse tier
    cannot take them; adaptive must)."""
    ds, cols = _make_ds()
    from spark_druid_olap_tpu.models.filters import And

    q = _query(
        filter=And(
            (InFilter("a", tuple(range(6))), InFilter("b", tuple(range(6))))
        ),
        aggs=(
            Count("n"),
            DoubleSum("s", "v"),
            HyperUnique("u", "k"),
        ),
    )
    eng = Engine(strategy="adaptive")
    got = eng.execute(q, ds)
    assert eng.last_metrics.strategy == "adaptive"
    mask = np.isin(cols["a"], range(6)) & np.isin(cols["b"], range(6))
    df = pd.DataFrame({k: v[mask] for k, v in cols.items()})
    want = df.groupby(["a", "b"]).agg(
        n=("v", "count"), s=("v", "sum"), u=("k", "nunique")
    ).reset_index()
    got = got.sort_values(["a", "b"]).reset_index(drop=True)
    want = want.sort_values(["a", "b"]).reset_index(drop=True)
    assert len(got) == len(want)
    np.testing.assert_array_equal(
        got["n"].astype(np.int64), want["n"].astype(np.int64)
    )
    # HLL is approximate: per-group counts are small here so the sparse
    # register path is near-exact; allow generous slack anyway
    err = np.abs(got["u"].astype(float) - want["u"].astype(float))
    assert (err <= np.maximum(2, 0.15 * want["u"])).all()


def test_adaptive_empty_filter_result():
    """A filter admitting NO code for some dim yields the empty grouped
    frame with the right columns (not a crash, not a full scan result)."""
    ds, cols = _make_ds()
    q = _query(filter=Selector("a", 99999))  # value not in the dictionary
    eng = Engine(strategy="adaptive")
    got = eng.execute(q, ds)
    assert len(got) == 0
    # same column set AND order as a real (non-empty) execution produces
    ref = Engine(strategy="segment").execute(_query(), ds)
    assert list(got.columns) == list(ref.columns)


def test_adaptive_not_used_for_explicit_segment():
    ds, cols = _make_ds()
    from spark_druid_olap_tpu.models.filters import And

    q = _query(
        filter=And(
            (InFilter("a", tuple(range(5))), InFilter("b", tuple(range(5))))
        )
    )
    eng = Engine(strategy="segment")
    got = eng.execute(q, ds)
    assert eng.last_metrics.strategy == "segment"
    mask = np.isin(cols["a"], range(5)) & np.isin(cols["b"], range(5))
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(_norm(got)["n"], want["n"])


def test_adaptive_matches_scatter_bit_for_bit_groups():
    """Adaptive and raw scatter agree on the full result frame (float sums
    compared tightly: both accumulate in f32 over the same per-segment
    order, modulo the domain re-key)."""
    ds, cols = _make_ds(segs=4)
    from spark_druid_olap_tpu.models.filters import And

    q = _query(
        filter=And(
            (InFilter("a", tuple(range(10))), InFilter("b", tuple(range(7))))
        )
    )
    a_df = _norm(Engine(strategy="adaptive").execute(q, ds))
    s_df = _norm(Engine(strategy="segment").execute(q, ds))
    np.testing.assert_array_equal(a_df[["a", "b", "n"]], s_df[["a", "b", "n"]])
    for c in ("s", "lo", "hi"):
        np.testing.assert_allclose(a_df[c], s_df[c], rtol=1e-6)


def test_adaptive_inner_kernels_follow_platform_not_static_resolver():
    """Regression for the round-4 bug class: every adaptive-tier program
    (presence pass, compact phase B) must pick its kernel from the
    platform/calibrated model, never the static auto resolver — on CPU
    the static choice lands on the dense one-hot, a ~200x inversion
    (measured 49-55s for SF10 passes that run sub-second on scatter)."""
    import jax

    from spark_druid_olap_tpu.models.filters import And

    if jax.devices()[0].platform != "cpu":
        import pytest

        pytest.skip("asserts the CPU-side routing")
    ds, cols = _make_ds()
    q = _query(
        filter=And(
            (InFilter("a", tuple(range(8))), InFilter("b", tuple(range(8))))
        )
    )
    eng = Engine(strategy="adaptive")
    eng.execute(q, ds)
    assert eng.last_metrics.strategy == "adaptive"
    adaptive_keys = [
        k for k in eng._query_fn_cache if "adaptive" in map(str, k[2:])
    ]
    assert adaptive_keys, "adaptive programs should be cached"
    for k in adaptive_keys:
        # k[2] is the kernel strategy element for compact phase-B programs
        assert k[2] != "dense", (
            f"compact program compiled with the dense one-hot on CPU: {k[2:]}"
        )


def test_filter_derived_kept_skips_presence_scan():
    """A filter that pins every grouping dim (In/Bound conjuncts) derives
    the kept sets from the dictionaries host-side: phase A must run ZERO
    device passes (the presence program is poisoned here), and parity
    must hold bit-for-bit with the scan-based path."""
    ds, cols = _make_ds()
    keep_a = tuple(range(3, 15))
    from spark_druid_olap_tpu.models.filters import And, Bound

    q = _query(
        filter=And(
            (
                InFilter("a", keep_a),
                Bound("b", lower=10, upper=30, ordering="numeric"),
            )
        )
    )
    eng = Engine(strategy="adaptive")

    def boom(*a, **k):  # pragma: no cover - fails the test if reached
        raise AssertionError("presence scan ran despite derivable filter")

    eng._presence_program = boom
    got = _norm(eng.execute(q, ds))
    mask = np.isin(cols["a"], keep_a) & (cols["b"] >= 10) & (cols["b"] <= 30)
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["a"], want["a"])
    np.testing.assert_array_equal(got["b"], want["b"])
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    assert eng.last_metrics.strategy == "adaptive"
    # derived kept = the accepted-code sets, already cached (derived
    # entries are segment-set independent: supersets by construction)
    tag, kept = eng._adaptive_kept[memo_key(q, ds)]
    assert tag == "derived"
    assert [int(x) for x in kept[0]] == sorted(keep_a)
    assert [int(x) for x in kept[1]] == list(range(10, 31))


def test_filter_derived_kept_declines_unpinned_dim():
    """A dim with no derivable conjunct (only an Or across dims) must NOT
    be derived — the scan-based phase A takes over and parity holds."""
    ds, cols = _make_ds()
    from spark_druid_olap_tpu.exec.adaptive_exec import filter_derived_kept
    from spark_druid_olap_tpu.exec.lowering import lower_groupby
    from spark_druid_olap_tpu.models.filters import And, Or

    q = _query(
        filter=And(
            (
                InFilter("a", (1, 2, 3)),
                Or((Selector("b", 5), Selector("a", 1))),
            )
        )
    )
    lowering = lower_groupby(q, ds)
    assert filter_derived_kept(q, lowering, ds) is None
    eng = Engine(strategy="adaptive")
    got = _norm(eng.execute(q, ds))
    mask = np.isin(cols["a"], (1, 2, 3)) & (
        (cols["b"] == 5) | (cols["a"] == 1)
    )
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
