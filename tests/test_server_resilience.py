"""Serving-layer resilience: admission control (503 + Retry-After),
structured error objects (no leaked internals), wire `context.timeout`
deadlines (504), and health consistency under concurrent load with faults
armed (ISSUE 1 satellites)."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.config import SessionConfig
from spark_druid_olap_tpu.resilience import injector
from spark_druid_olap_tpu.server import OlapServer


@pytest.fixture(autouse=True)
def _clean_injector():
    injector().disarm()
    yield
    injector().disarm()


def _make_ctx(**overrides):
    cfg = SessionConfig.load_calibrated()
    cfg.result_cache_entries = 0
    cfg.retry_backoff_ms = 1.0
    for k, v in overrides.items():
        setattr(cfg, k, v)
    ctx = sd.TPUOlapContext(cfg)
    n = 4_000
    rng = np.random.default_rng(11)
    ctx.register_table(
        "ev",
        {
            "city": rng.choice(
                np.array(["NY", "SF", "LA"], dtype=object), n
            ),
            "v": rng.random(n).astype(np.float32),
        },
        dimensions=["city"],
        metrics=["v"],
    )
    return ctx


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=30
    ) as r:
        return json.loads(r.read())


def _health_drained(port):
    """Health snapshot once slots drain (the handler releases its slot a
    hair after the response bytes land — poll out the benign race)."""
    h = None
    for _ in range(100):
        h = _get(port, "/status/health")
        if h["admission"]["slots_in_use"] == 0:
            return h
        time.sleep(0.01)
    return h


def _post(port, path, payload, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


_SQL = {"query": "SELECT city, sum(v) AS s FROM ev GROUP BY city"}


def test_structured_500_no_internal_leak():
    ctx = _make_ctx(fallback_execution=False)
    srv = OlapServer(ctx, port=0).start()
    try:
        injector().arm("device_dispatch", "error")
        code, body, _ = _post(srv.port, "/druid/v2/sql", _SQL)
        assert code == 500
        # structured Druid-style error object, raw exception text withheld
        assert set(body) == {"error", "errorMessage", "errorClass"}
        assert body["errorClass"] == "InjectedFault"
        for v in body.values():
            assert "Traceback" not in v
            assert "injected fault at site" not in v  # raw str(e) withheld
        # the failure is recorded on the health counters
        h = _get(srv.port, "/status/health")
        assert h["counters"]["server_errors_total"] >= 1
        assert h["counters"]["last_error"]["errorClass"] == "InjectedFault"
    finally:
        srv.shutdown()


def test_client_errors_keep_readable_message():
    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        code, body, _ = _post(
            srv.port, "/druid/v2",
            {"queryType": "groupBy", "dataSource": "nope",
             "dimensions": [], "aggregations": []},
        )
        assert code == 400
        assert "unknown dataSource" in body["error"]
        assert body["errorClass"]
    finally:
        srv.shutdown()


def test_wire_context_timeout_yields_504():
    """With partial results DECLINED (context.partialResults=false, the
    pre-ISSUE-7 contract), a blown wire deadline is still a hard 504."""
    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        # a 150ms device stall against a 30ms wire deadline: the resolve
        # checkpoint fires deterministically after the injected delay
        injector().arm("device_dispatch", "delay", delay_ms=150)
        code, body, _ = _post(
            srv.port, "/druid/v2/sql",
            {**_SQL, "context": {"timeout": 30, "partialResults": False}},
        )
        assert code == 504
        assert body["errorClass"] == "QueryTimeoutException"
        assert "deadline" in body["error"]
        h = _get(srv.port, "/status/health")
        assert h["counters"]["deadline_exceeded_total"] >= 1
    finally:
        srv.shutdown()


def test_wire_deadline_with_partials_yields_coverage_stamped_200():
    """The ISSUE 7 default: a deadline expiring mid-scan returns 200
    with the best-effort answer and the partial contract in
    X-Druid-Response-Context instead of a 504.  The expiry is pinned to
    the scan's first checkpoint with an injected deadline (clock-free,
    deterministic)."""
    import json as _json

    from spark_druid_olap_tpu.resilience import InjectedDeadline

    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        injector().arm(
            "engine.segment_loop", "error", times=1,
            error_type=InjectedDeadline,
        )
        code, body, headers = _post(srv.port, "/druid/v2/sql", _SQL)
        assert code == 200
        rc = headers.get("X-Druid-Response-Context")
        assert rc, "partial answers must carry the response context"
        info = _json.loads(rc)
        assert info["partial"] is True
        assert info["coverage"] == 0.0  # expired before the first batch
        assert info["rows_seen"] == 0 and info["rows_total"] > 0
    finally:
        srv.shutdown()


def test_admission_503_carries_retry_after():
    ctx = _make_ctx(
        max_concurrent_queries=1, admission_queue_timeout_ms=60
    )
    srv = OlapServer(ctx, port=0).start()
    try:
        injector().arm("device_dispatch", "delay", delay_ms=400)
        results = []
        lock = threading.Lock()

        def hit():
            r = _post(srv.port, "/druid/v2/sql", _SQL)
            with lock:
                results.append(r)

        threads = [threading.Thread(target=hit) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        codes = sorted(c for c, _, _ in results)
        assert 503 in codes  # the pool is 1 wide: someone was rejected
        for code, body, headers in results:
            if code == 503:
                assert body["errorClass"] == "QueryCapacityExceededException"
                assert int(headers["Retry-After"]) >= 1
            else:
                assert code == 200
        # slots drain fully once the burst is over
        h = _health_drained(srv.port)
        assert h["admission"]["slots_in_use"] == 0
        assert h["admission"]["rejected_total"] >= 1
    finally:
        srv.shutdown()


def test_concurrent_hammer_with_faults_no_unstructured_500s():
    """N threads against /druid/v2/sql while device faults are armed: every
    response is 200 (degraded fallback answers) or a STRUCTURED error;
    /status/health stays consistent before/during/after the tripped
    breaker."""
    ctx = _make_ctx(
        max_concurrent_queries=2,
        admission_queue_timeout_ms=100,
        breaker_failure_threshold=2,
        breaker_cooldown_ms=600_000,
    )
    srv = OlapServer(ctx, port=0).start()
    try:
        h0 = _get(srv.port, "/status/health")
        assert h0["breaker"]["state"] == "closed"

        injector().arm("device_dispatch", "error")
        results = []
        lock = threading.Lock()

        def hit():
            r = _post(srv.port, "/druid/v2/sql", _SQL)
            with lock:
                results.append(r)
            # health must stay servable mid-storm
            h = _get(srv.port, "/status/health")
            assert h["breaker"]["state"] in ("closed", "open", "half_open")
            assert (
                0
                <= h["admission"]["slots_in_use"]
                <= h["admission"]["slots_total"]
            )

        threads = [threading.Thread(target=hit) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(results) == 8
        want = ctx.sql(_SQL["query"]).sort_values("city")
        for code, body, headers in results:
            if code == 200:
                got = sorted(r["city"] for r in body)
                assert got == list(want["city"])
            else:
                # every error is structured — no unstructured 500s
                assert isinstance(body, dict) and "errorClass" in body, body
                assert code in (500, 503, 504)
                if code == 503:
                    assert "Retry-After" in headers
        # the failure storm tripped the breaker; health reports it and
        # all slots drained
        h1 = _health_drained(srv.port)
        assert h1["breaker"]["state"] == "open"
        assert h1["admission"]["slots_in_use"] == 0
        assert h1["counters"]["degraded_total"] >= 1

        # after disarm + cooldown the breaker closes again on a probe
        injector().disarm()
        ctx.resilience.breaker.cooldown_ms = 0.0
        code, body, _ = _post(srv.port, "/druid/v2/sql", _SQL)
        assert code == 200
        h2 = _health_drained(srv.port)
        assert h2["breaker"]["state"] == "closed"
        assert h2["admission"]["slots_in_use"] == 0
    finally:
        srv.shutdown()


def test_native_path_degrades_while_breaker_open():
    """Native wire queries used to 503 on an open breaker (no logical
    plan to degrade with).  ISSUE 7 completes the degradation matrix:
    the QuerySpec decodes to a logical plan and answers on the host
    fallback — still without burning retry budget against the
    known-bad device."""
    ctx = _make_ctx(breaker_failure_threshold=1, breaker_cooldown_ms=600_000)
    srv = OlapServer(ctx, port=0).start()
    native = {
        "queryType": "timeseries",
        "dataSource": "ev",
        "granularity": "all",
        "aggregations": [{"type": "count", "name": "n"}],
    }
    try:
        injector().arm("device_dispatch", "error")
        ctx.sql(_SQL["query"])  # trips the breaker (threshold 1)
        assert "open" in {
            br.state for br in ctx.resilience.breakers.values()
        }
        # force the DEVICE breaker open too (the SQL warm-up may have
        # tripped only the mesh breaker on a distributed plan): the
        # native route consults the device breaker
        dev = ctx.resilience.breaker_for("device")
        for _ in range(dev.failure_threshold):
            dev.record_failure()
        assert dev.state == "open"
        fired = injector().state()["fired"].get("device_dispatch", 0)
        code, body, headers = _post(srv.port, "/druid/v2", native)
        assert code == 200
        assert body[0]["result"]["n"] > 0  # a real degraded answer
        # degraded, not retried: no device attempt reached the injector
        assert injector().state()["fired"].get("device_dispatch", 0) == fired
        h = _get(srv.port, "/status/health")
        assert h["counters"]["degraded_total"] >= 1
        # SQL still answers (degraded) through the same open breaker
        code, rows, _ = _post(srv.port, "/druid/v2/sql", _SQL)
        assert code == 200
    finally:
        srv.shutdown()


def test_context_timeout_zero_disables_session_deadline():
    """Druid semantics: `context.timeout: 0` means NO timeout and must
    override a session default, not fall through to it; a non-dict
    context is client noise (ignored), not a 500."""
    ctx = _make_ctx(query_timeout_ms=30)
    srv = OlapServer(ctx, port=0).start()
    try:
        injector().arm("device_dispatch", "delay", delay_ms=120)
        # session deadline (30ms) would 504 this — timeout:0 opts out
        code, rows, _ = _post(
            srv.port, "/druid/v2/sql", {**_SQL, "context": {"timeout": 0}}
        )
        assert code == 200 and len(rows) == 3
        # a string context must not become a 500
        injector().disarm()
        code, rows, _ = _post(
            srv.port, "/druid/v2/sql", {**_SQL, "context": "fast"}
        )
        assert code == 200
    finally:
        srv.shutdown()


def test_non_groupby_probe_closes_breaker():
    """A half-open probe served by a SCAN query must still close the
    breaker (breaker accounting is not GroupBy-only)."""
    ctx = _make_ctx(breaker_failure_threshold=1, breaker_cooldown_ms=600_000)
    injector().arm("device_dispatch", "error")
    ctx.sql(_SQL["query"])  # trips it
    assert ctx.resilience.breaker.state == "open"
    injector().disarm()
    ctx.resilience.breaker.cooldown_ms = 0.0
    df = ctx.sql("SELECT city FROM ev LIMIT 5")  # scan path probe
    assert len(df) == 5
    assert ctx.resilience.breaker.state == "closed"


def test_non_object_json_body_is_400():
    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        code, body, _ = _post(srv.port, "/druid/v2/sql", [1, 2, 3])
        assert code == 400
        assert body["errorClass"] == "BadJsonQueryException"
    finally:
        srv.shutdown()


def test_breaker_open_serves_result_cache_hits():
    """A cached exact device answer must not be re-paid on the host
    interpreter just because the breaker is open."""
    cfg_overrides = dict(
        breaker_failure_threshold=1, breaker_cooldown_ms=600_000
    )
    ctx = _make_ctx(**cfg_overrides)
    ctx.config.result_cache_entries = 8  # cache ON for this test
    q = _SQL["query"]
    want = ctx.sql(q)
    assert ctx.last_metrics.executor == "device"
    injector().arm("device_dispatch", "error")
    ctx.sql("SELECT count(*) AS n FROM ev WHERE city = 'NY'")  # trips it
    assert ctx.resilience.breaker.state == "open"
    injector().disarm()
    got = ctx.sql(q)  # same query: served from the result cache
    m = ctx.last_metrics
    assert m.strategy == "result-cache"
    assert m.executor == "device" and not m.degraded
    assert list(got["s"]) == list(want["s"])


def test_status_includes_resilience_block():
    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        st = _get(srv.port, "/status")
        assert st["resilience"]["breaker"]["state"] == "closed"
        assert st["resilience"]["admission"]["slots_total"] >= 1
    finally:
        srv.shutdown()
