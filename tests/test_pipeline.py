"""Overlapped h2d transfer pipeline (ISSUE 10 tentpole).

Layers under test:

1. Oracle parity: pipeline-on results are BYTE-identical to
   pipeline-off across the dense, sparse, fused, scan, and streaming
   executors (the fold order is pinned to canonical batch order, so
   residency-aware dispatch reordering cannot reassociate f32 sums).
2. Prefetch mechanics: the plan issues async puts for upcoming batches,
   orders resident batches first, and speculates on next-interval
   segments under the separate byte cap.
3. Lifecycle edges: a pending prefetch cancels cleanly on deadline
   expiry mid-stream, an append/compaction retiring a queued uid stops
   its issue, a budget eviction racing a landing prefetch leaks no
   phantom resident bytes, and an injected `h2d` fault on a PREFETCHED
   put is re-raised at consume — reaching the retry machinery exactly
   like a foreground transfer failure.
4. Attribution: sampled cost receipts carry `overlap_efficiency` and
   the prefetch bucket; the fused-batch CSE plan (serve/fusion.
   shared_row_plan) groups identical sub-lowerings.
"""

import numpy as np
import pandas as pd
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.catalog.segment import build_datasource
from spark_druid_olap_tpu.config import SessionConfig
from spark_druid_olap_tpu.exec.engine import Engine, segments_in_scope
from spark_druid_olap_tpu.models.aggregations import Count, DoubleSum
from spark_druid_olap_tpu.models.dimensions import DimensionSpec
from spark_druid_olap_tpu.models.query import GroupByQuery, ScanQuery
from spark_druid_olap_tpu.resilience import (
    InjectedDeadline,
    InjectedFault,
    deadline_scope,
    injector,
    partial_scope,
)


@pytest.fixture(autouse=True)
def _clean_injector():
    injector().disarm()
    yield
    injector().disarm()


def _ctx(**overrides):
    cfg = SessionConfig.load_calibrated()
    cfg.result_cache_entries = 0
    cfg.retry_backoff_ms = 1.0
    cfg.prefer_distributed = False
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return sd.TPUOlapContext(cfg)


def _flat_ds(n=8_192, seg_rows=512, name="pl", card=4, seed=3):
    """Multi-segment datasource: small segments so the CPU unroll cap
    (2) yields MANY dispatch batches — the shape the pipeline reorders
    and prefetches across."""
    rng = np.random.default_rng(seed)
    cols = {
        "d": np.array(
            [f"k{i}" for i in rng.integers(0, card, size=n)], dtype=object
        ),
        "v": rng.random(n).astype(np.float32),
        "t": (np.arange(n) * 1_000).astype(np.int64),
    }
    ds = build_datasource(
        name, cols, dimension_cols=["d"], metric_cols=["v"],
        time_col="t", rows_per_segment=seg_rows,
    )
    return ds, cols


def _gb(ds_name="pl", filt=None, intervals=()):
    return GroupByQuery(
        datasource=ds_name,
        dimensions=(DimensionSpec("d"),),
        aggregations=(Count("n"), DoubleSum("s", "v")),
        filter=filt,
        intervals=tuple(intervals),
    )


def _exact_equal(a, b):
    pd.testing.assert_frame_equal(
        a.reset_index(drop=True), b.reset_index(drop=True), check_exact=True
    )


# ---------------------------------------------------------------------------
# 1. oracle parity: pipeline-on == pipeline-off, byte-identical
# ---------------------------------------------------------------------------


def test_dense_parity_on_vs_off():
    ds, _ = _flat_ds()
    q = _gb()
    on = Engine()
    off = Engine()
    off._pipeline.enabled = False
    _exact_equal(on.execute(q, ds), off.execute(q, ds))
    # warm repeat (fully resident) stays identical too
    _exact_equal(on.execute(q, ds), off.execute(q, ds))


def test_dense_parity_after_partial_residency():
    """A prewarmed subset flips the dispatch order (resident batches
    first) — results must stay byte-identical to the cold canonical
    order."""
    ds, _ = _flat_ds(name="pl2")
    q = _gb("pl2")
    off = Engine()
    off._pipeline.enabled = False
    want = off.execute(q, ds)
    on = Engine()
    # prewarm a LATE interval slice so canonical order starts cold
    warm = _gb("pl2", intervals=[(6_000_000, 8_192_000)])
    on.execute(warm, ds)
    _exact_equal(on.execute(q, ds), want)


def test_sparse_parity_on_vs_off():
    rng = np.random.default_rng(11)
    n = 40_000
    cols = {
        "a": rng.integers(0, 300, size=n),
        "b": rng.integers(0, 300, size=n),
        "v": np.ones(n, np.float32),
    }
    from spark_druid_olap_tpu.catalog.segment import DimensionDict

    ds = build_datasource(
        "plsp", cols, dimension_cols=["a", "b"], metric_cols=["v"],
        rows_per_segment=1 << 13,
        dicts={
            "a": DimensionDict(values=tuple(range(300))),
            "b": DimensionDict(values=tuple(range(300))),
        },
    )
    q = GroupByQuery(
        datasource="plsp",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(Count("n"), DoubleSum("s", "v")),
    )
    on = Engine(strategy="sparse")
    off = Engine(strategy="sparse")
    off._pipeline.enabled = False
    _exact_equal(on.execute(q, ds), off.execute(q, ds))


def test_fused_parity_on_vs_off():
    ds, _ = _flat_ds(name="plf")
    from spark_druid_olap_tpu.models.filters import Selector

    queries = [_gb("plf"), _gb("plf", filt=Selector("d", "k1")), _gb("plf")]
    on = Engine()
    off = Engine()
    off._pipeline.enabled = False
    got = on.execute_fused(queries, ds)
    want = off.execute_fused(queries, ds)
    for (df_on, _, _), (df_off, _, _) in zip(got, want):
        _exact_equal(df_on, df_off)
    # fused members must also equal their own serial executions
    for (df_on, _, _), q in zip(got, queries):
        _exact_equal(df_on, off.execute(q, ds))


def test_scan_parity_and_row_order_on_vs_off():
    ds, _ = _flat_ds(name="plsc")
    q = ScanQuery(datasource="plsc", columns=("d", "v"), limit=700)
    on = Engine()
    off = Engine()
    off._pipeline.enabled = False
    # scan dispatch stays canonical (reorder=False): LIMIT semantics and
    # row order are part of the result contract
    _exact_equal(on.execute(q, ds), off.execute(q, ds))


def test_streaming_parity_on_vs_off():
    from spark_druid_olap_tpu.exec.streaming import StreamExecutor
    from spark_druid_olap_tpu.utils import datagen

    q_inner = GroupByQuery(
        datasource="events",
        dimensions=(),
        aggregations=(Count("n"), DoubleSum("s", "value")),
        intervals=(datagen.event_stream_interval(),),
    )
    ds = datagen.event_stream_schema()
    chunk = 1 << 12
    staged = [datagen.gen_event_chunk(i, chunk) for i in range(5)]
    eng_on = Engine()
    eng_off = Engine()
    eng_off._pipeline.enabled = False
    got = StreamExecutor(engine=eng_on).execute(
        q_inner, ds, iter(staged), chunk
    )
    want = StreamExecutor(engine=eng_off).execute(
        q_inner, ds, iter(staged), chunk
    )
    _exact_equal(got, want)


# ---------------------------------------------------------------------------
# 2. prefetch mechanics
# ---------------------------------------------------------------------------


def test_prefetch_issues_and_scope_lands_resident():
    from spark_druid_olap_tpu.exec.arena import arena_disabled

    ds, _ = _flat_ds(name="plm")
    eng = Engine()
    # loop-path mechanics under test: the arena would stack the scope
    # into one resident buffer instead of per-segment columns
    with arena_disabled():
        eng.execute(_gb("plm"), ds)
    assert eng._pipeline.issued > 0
    # every in-scope column landed in the residency cache
    for seg in ds.segments:
        assert (seg.uid, "col", "d") in eng._device_cache
        assert (seg.uid, "valid") in eng._device_cache


def test_residency_aware_order_runs_resident_batches_first():
    ds, _ = _flat_ds(name="plo")
    eng = Engine()
    need = ["d", "v"]
    batches = list(eng._segment_batches(list(ds.segments), need))
    assert len(batches) >= 4
    # warm exactly the SECOND batch's columns
    for seg in batches[1]:
        eng._device_cols(seg, need, ds_name=ds.name)
    run = eng._pipeline.start(ds, batches, need)
    # within the first reorder window, the resident batch dispatches
    # first; canonical order is preserved among equally-cold batches
    assert run.order[0] == 1
    assert run.order[1] == 0
    # disabled pipeline keeps canonical order
    eng2 = Engine()
    eng2._pipeline.enabled = False
    run2 = eng2._pipeline.start(ds, batches, need)
    assert run2.order == list(range(len(batches)))


def test_speculative_prefetch_respects_byte_cap():
    ds, _ = _flat_ds(name="plsv")
    # scope = the first quarter of the time range; the rest of the
    # segments are speculative candidates
    q = _gb("plsv", intervals=[(0, 2_048_000)])
    eng = Engine()
    eng._pipeline.speculative_bytes = 64 << 20
    segs = segments_in_scope(q, ds)
    assert 0 < len(segs) < len(ds.segments)
    eng.execute(q, ds)
    assert eng._pipeline.speculative_issued > 0
    out_of_scope = [
        s for s in ds.segments if s.uid not in {x.uid for x in segs}
    ]
    assert any(
        (s.uid, "col", "d") in eng._device_cache for s in out_of_scope
    )
    # a tiny cap stops speculation almost immediately
    eng2 = Engine()
    eng2._pipeline.speculative_bytes = 1  # 1 byte: first entry exceeds it
    eng2.execute(q, ds)
    assert eng2._pipeline.speculative_issued <= 1


def test_speculative_candidates_next_interval_first():
    ds, _ = _flat_ds(name="plnx")
    q = _gb("plnx", intervals=[(2_048_000, 4_096_000)])
    eng = Engine()
    eng._pipeline.speculative_bytes = 1 << 20
    segs = segments_in_scope(q, ds)
    cands = eng._pipeline.speculative_candidates(q, ds, segs)
    assert cands, "out-of-scope segments should be candidates"
    scope_end = max(s.interval[1] for s in segs)
    # the first candidates are the NEXT intervals, not the earlier ones
    assert cands[0].interval[0] >= scope_end


# ---------------------------------------------------------------------------
# 3. lifecycle edges
# ---------------------------------------------------------------------------


def test_deadline_expiry_cancels_pending_prefetch():
    ctx = _ctx()
    n = 20_000
    ctx.register_table(
        "t",
        {
            "d": np.array(["a", "b", "c", "d"] * (n // 4), dtype=object),
            "v": np.ones(n, dtype=np.float32),
        },
        dimensions=["d"],
        metrics=["v"],
        rows_per_segment=1 << 10,
    )
    injector().arm(
        "engine.segment_loop", "error", times=1, skip=2,
        error_type=InjectedDeadline,
    )
    before = ctx.engine._pipeline.cancelled
    with deadline_scope(60_000), partial_scope(True):
        df = ctx.sql("SELECT d, COUNT(*) AS n FROM t GROUP BY d")
    assert df.attrs["partial"] is True
    assert 0 < df.attrs["coverage"] < 1.0
    assert ctx.engine._pipeline.cancelled > before


def test_retired_uid_skips_queued_prefetch():
    ds, _ = _flat_ds(name="plr")
    eng = Engine()
    need = ["d", "v"]
    batches = list(eng._segment_batches(list(ds.segments), need))
    assert len(batches) >= 3
    run = eng._pipeline.start(ds, batches, need)
    # an append/compaction retires the segments of the 2nd + 3rd batch
    # AFTER the plan was built but BEFORE their prefetch issues
    retired = {s.uid for b in batches[1:3] for s in b}
    eng.evict_segments(retired)
    run.advance(0)  # would have prefetched batches 1..2
    assert eng._pipeline.skipped_retired > 0
    for uid in retired:
        assert (uid, "valid") not in eng._device_cache
        assert (uid, "col", "d") not in eng._device_cache


def test_budget_eviction_racing_landing_prefetch_leaks_no_bytes():
    ds, cols = _flat_ds(name="plb")
    one_seg_bytes = int(ds.segments[0].valid.nbytes) + sum(
        int(ds.segments[0].column(c).nbytes) for c in ("d", "v")
    )
    # budget only ~1.5 batches: prefetched entries are budget-evicted
    # almost as soon as they land
    eng = Engine(device_cache_bytes=3 * one_seg_bytes)
    df = eng.execute(_gb("plb"), ds)
    assert int(df["n"].sum()) == len(cols["v"])
    # phantom-byte check: per-datasource residency accounting must agree
    # with the cache's own byte count after all the eviction churn
    assert sum(eng._resident_by_ds.values()) == eng._device_cache.bytes_used
    assert eng._device_cache.bytes_used <= 3 * one_seg_bytes


def test_injected_h2d_fault_on_prefetched_put_reaches_retry():
    ds, cols = _flat_ds(name="plh")
    eng = Engine()
    need_keys_per_batch = sum(
        2 + 1 for _ in range(2)
    )  # 2 cols + valid, 2 segs/batch on CPU
    # skip past batch 0's foreground puts so the fault fires on a
    # PREFETCHED put (issued by run.advance), then is re-raised at
    # consume and absorbed by the engine's transient retry.  Loop-path
    # machinery under test (the arena path has its own put cadence).
    from spark_druid_olap_tpu.exec.arena import arena_disabled

    injector().arm("h2d", "error", times=1, skip=need_keys_per_batch)
    with arena_disabled():
        df = eng.execute(_gb("plh"), ds)
    assert int(df["n"].sum()) == len(cols["v"])
    assert eng.last_metrics.retries == 1


def test_injected_h2d_fault_without_retries_surfaces():
    from spark_druid_olap_tpu.exec.arena import arena_disabled

    ds, _ = _flat_ds(name="plh2")
    eng = Engine()
    eng._retry_attempts = 1  # no retry budget
    injector().arm("h2d", "error", times=1, skip=6)
    with pytest.raises(InjectedFault), arena_disabled():
        eng.execute(_gb("plh2"), ds)


# ---------------------------------------------------------------------------
# 4. attribution + CSE plan
# ---------------------------------------------------------------------------


def test_sampled_receipt_carries_overlap_fields():
    ctx = _ctx(prof_sample_rate=0.0)
    ds, _ = _flat_ds(name="plrc")
    ctx.register_table(
        "plrc",
        {
            "d": np.array(["a", "b"] * 2048, dtype=object),
            "v": np.ones(4096, np.float32),
        },
        dimensions=["d"],
        metrics=["v"],
        rows_per_segment=512,
    )
    ctx.tracer.force_sample_next()
    df = ctx.sql("SELECT d, SUM(v) FROM plrc GROUP BY d")
    rc = df.attrs.get("receipt")
    assert rc is not None
    assert "overlap_efficiency" in rc
    assert 0.0 <= rc["overlap_efficiency"] <= 1.0
    assert "prefetch_ms" in rc and "prefetch_bytes" in rc
    assert rc["sampled"] is True


def test_shared_row_plan_groups_identical_sublowerings():
    from spark_druid_olap_tpu.models.filters import Selector
    from spark_druid_olap_tpu.serve.fusion import shared_row_plan

    a = _gb(filt=Selector("d", "k1"))
    b = _gb(filt=Selector("d", "k1"))  # same filter + dims as a
    c = _gb(filt=Selector("d", "k2"))  # different filter, same dims
    plan = shared_row_plan([a, b, c])
    assert plan[0] == (0, 0)
    assert plan[1] == (0, 0)  # mask AND gid shared with a
    assert plan[2][0] == 2  # its own mask group
    assert plan[2][1] == 0  # gid still shared (same dimensions)


def test_fused_cse_traces_shared_filter_once():
    """Two members with an identical filter must evaluate it ONCE per
    segment inside the fused program (ROADMAP 1(a)): count filter_fn
    invocations at trace time."""
    from spark_druid_olap_tpu.models.filters import Selector

    ds, _ = _flat_ds(name="plcse")
    eng = Engine()
    queries = [
        _gb("plcse", filt=Selector("d", "k1")),
        GroupByQuery(
            datasource="plcse",
            dimensions=(DimensionSpec("d"),),
            aggregations=(DoubleSum("s2", "v"),),
            filter=Selector("d", "k1"),
        ),
    ]
    calls = {"n": 0}
    lowerings = [eng._lowering_for(q, ds) for q in queries]
    for lo in lowerings:
        orig = lo.filter_fn

        def counting(cols, _orig=orig):
            calls["n"] += 1
            return _orig(cols)

        lo.filter_fn = counting
    from spark_druid_olap_tpu.exec.arena import arena_disabled

    # loop-path CSE under test: the arena program traces each shared
    # sub-lowering once per SCAN BODY (one block), not once per segment
    with arena_disabled():
        out = eng.execute_fused(queries, ds)
    batches = list(eng._segment_batches(list(ds.segments), ["d", "v"]))
    # every batch has the same member->segment selection, so ONE program
    # traces (and is reused across batches): the shared filter evaluates
    # once per segment IN THE TRACE — not once per (member, segment),
    # which would be 2x
    assert calls["n"] == len(batches[0]), (calls["n"], len(batches[0]))
    # and the answers are still each member's own
    off = Engine()
    off._pipeline.enabled = False
    for (df, _, _), q in zip(out, queries):
        _exact_equal(df, off.execute(q, ds))


def test_fused_time_bucketed_members_with_shifted_intervals():
    """Review regression: the CSE gid signature must include intervals.
    Two members with the SAME time-bucket dimension over SHIFTED
    intervals compute different gids (the bucket origin/cardinality
    close over the interval span); sharing them returned silently wrong
    aggregates for the second member."""
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec as D

    ds, _ = _flat_ds(name="plti", n=8_192, seg_rows=512)

    def bucketed(lo, hi):
        return GroupByQuery(
            datasource="plti",
            dimensions=(D("__time", granularity="minute"),),
            aggregations=(Count("n"), DoubleSum("s", "v")),
            intervals=((lo, hi),),
        )

    a = bucketed(0, 2_048_000)
    b = bucketed(1_024_000, 3_072_000)  # same dims, shifted interval
    eng = Engine()
    out = eng.execute_fused([a, b], ds)
    serial = Engine()
    serial._pipeline.enabled = False
    for (df, _, _), q in zip(out, (a, b)):
        _exact_equal(df, serial.execute(q, ds))


def test_stale_poison_dies_with_its_truncated_owner():
    """Review regression: poisons are RUN-scoped.  A prefetch that fails
    inside a query which then truncates before consuming it (here: a
    scan satisfying its LIMIT after one segment) must NOT leak the
    failure into a later query's cache miss — the later query attempts
    a fresh transfer and succeeds with ZERO retries."""
    ds, cols = _flat_ds(name="plps")
    eng = Engine()
    # scan fetches d, v, t (+ valid) = 4 puts for segment 0, then
    # advance(0) prefetches segment 1: skip past the foreground puts so
    # the fault lands on segment 1's FIRST prefetched put
    injector().arm("h2d", "error", times=1, skip=4)
    q = ScanQuery(datasource="plps", columns=("d", "v"), limit=10)
    df = eng.execute(q, ds)  # LIMIT met on segment 0: run cancelled
    assert len(df) == 10
    injector().disarm()
    # the poisoned column never got consumed by its owner; a later
    # query must not inherit the failure
    got = eng.execute(_gb("plps"), ds)
    assert int(got["n"].sum()) == len(cols["v"])
    assert eng.last_metrics.retries == 0, "stale poison leaked"
