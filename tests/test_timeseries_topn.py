"""Timeseries / TopN / granularity semantics vs pandas oracle."""

import numpy as np
import pandas as pd

from spark_druid_olap_tpu.exec.engine import Engine
from spark_druid_olap_tpu.models.aggregations import Count, DoubleSum
from spark_druid_olap_tpu.models.dimensions import DimensionSpec
from spark_druid_olap_tpu.models.query import (
    GroupByQuery,
    TimeseriesQuery,
    TopNQuery,
)
from spark_druid_olap_tpu.utils.granularity import bucket_starts

_MS_DAY = 86_400_000


def test_timeseries_month_rollup(lineitem_ds, lineitem_cols):
    q = TimeseriesQuery(
        datasource="tpch",
        granularity="month",
        aggregations=(DoubleSum("rev", "l_extendedprice"), Count("n")),
    )
    got = Engine().execute(q, lineitem_ds)
    t = np.asarray(lineitem_cols["l_shipdate"]).astype("datetime64[ms]")
    df = pd.DataFrame(
        {
            "m": t.astype("datetime64[M]"),
            "p": np.asarray(lineitem_cols["l_extendedprice"], np.float64),
        }
    )
    want = df.groupby("m", sort=True).agg(rev=("p", "sum"), n=("p", "size"))
    assert len(got) == len(want)
    np.testing.assert_array_equal(
        got.timestamp.values.astype("datetime64[M]"), want.index.values
    )
    np.testing.assert_array_equal(got.n, want.n)
    np.testing.assert_allclose(got.rev, want.rev, rtol=2e-5)


def test_timeseries_empty_buckets_kept():
    """skip_empty_buckets=False zero-fills gaps (Druid default parity)."""
    from spark_druid_olap_tpu.catalog.segment import build_datasource

    t = np.array([0, 2 * _MS_DAY, 2 * _MS_DAY + 5])  # gap at day 1
    ds = build_datasource(
        "gap",
        {"t": t, "x": np.array([1.0, 2.0, 3.0], np.float32)},
        dimension_cols=[],
        metric_cols=["x"],
        time_col="t",
    )
    q = TimeseriesQuery(
        datasource="gap",
        granularity="day",
        aggregations=(Count("n"), DoubleSum("s", "x")),
        skip_empty_buckets=False,
    )
    got = Engine().execute(q, ds)
    assert len(got) == 3
    assert list(got.n) == [1, 0, 2]
    assert list(got.s) == [1.0, 0.0, 5.0]

    got2 = Engine().execute(
        TimeseriesQuery(
            datasource="gap",
            granularity="day",
            aggregations=(Count("n"),),
            skip_empty_buckets=True,
        ),
        ds,
    )
    assert list(got2.n) == [1, 2]


def test_week_buckets_monday_aligned():
    # 2024-01-01 is a Monday; it must start its own bucket.
    monday = int(np.datetime64("2024-01-01").astype("datetime64[ms]").astype(int))
    sunday = monday - _MS_DAY
    starts = bucket_starts(sunday, monday + _MS_DAY, "week")
    # epoch day 0 = Thursday, so Mondays are day ≡ 4 (mod 7)
    days = (starts // _MS_DAY) % 7
    assert (days == 4).all()
    assert monday in starts.tolist()


def test_empty_interval_returns_zero_rows(lineitem_ds):
    q = GroupByQuery(
        datasource="tpch",
        dimensions=(DimensionSpec("l_returnflag"),),
        aggregations=(Count("n"),),
        intervals=((0, 1000),),  # 1970: nothing in scope
    )
    got = Engine().execute(q, lineitem_ds)
    assert len(got) == 0


def test_topn_exact(ssb_ds, ssb_cols):
    q = TopNQuery(
        datasource="ssb",
        dimension=DimensionSpec("c_city"),
        metric="rev",
        threshold=10,
        aggregations=(DoubleSum("rev", "lo_revenue"),),
    )
    got = Engine().execute(q, ssb_ds)
    df = pd.DataFrame(
        {
            "c": np.asarray(ssb_cols["c_city"], dtype=object),
            "r": np.asarray(ssb_cols["lo_revenue"], np.float64),
        }
    )
    want = df.groupby("c").r.sum().sort_values(ascending=False).head(10)
    assert list(got.c_city) == list(want.index)
    np.testing.assert_allclose(got.rev, want.values, rtol=2e-5)


def test_groupby_granularity_year(ssb_ds, ssb_cols):
    q = GroupByQuery(
        datasource="ssb",
        dimensions=(DimensionSpec("s_region"),),
        aggregations=(Count("n"),),
        granularity="year",
    )
    got = Engine().execute(q, ssb_ds)
    t = np.asarray(ssb_cols["lo_orderdate"]).astype("datetime64[ms]")
    df = pd.DataFrame(
        {
            "y": t.astype("datetime64[Y]"),
            "r": np.asarray(ssb_cols["s_region"], dtype=object),
        }
    )
    want = df.groupby(["y", "r"]).size().reset_index(name="n")
    got = got.sort_values(["timestamp", "s_region"]).reset_index(drop=True)
    want = want.sort_values(["y", "r"]).reset_index(drop=True)
    assert len(got) == len(want)
    np.testing.assert_array_equal(got.n, want.n)


def test_chained_virtual_columns():
    """Review finding: a virtual column reading ANOTHER virtual column
    (declaration order) must lower without fetching the intermediate name
    from segments."""
    import numpy as np

    from spark_druid_olap_tpu.catalog.segment import build_datasource
    from spark_druid_olap_tpu.exec.engine import Engine
    from spark_druid_olap_tpu.models.aggregations import DoubleSum
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec
    from spark_druid_olap_tpu.models.query import GroupByQuery, VirtualColumn
    from spark_druid_olap_tpu.plan.expr import Literal, col

    g = np.array([0, 1, 0, 1, 0], dtype=np.int64)
    v = np.array([1.0, 2.0, 3.0, 4.0, 5.0], dtype=np.float32)
    ds = build_datasource(
        "cv", {"g": g, "v": v}, dimension_cols=["g"], metric_cols=["v"]
    )
    q = GroupByQuery(
        datasource="cv",
        dimensions=(DimensionSpec("g"),),
        aggregations=(DoubleSum("s", "b"),),
        virtual_columns=(
            VirtualColumn("a", col("v") * Literal(2.0)),
            VirtualColumn("b", col("a") + Literal(1.0)),
        ),
    )
    got = Engine().execute(q, ds)
    by = {int(r["g"]): float(r["s"]) for _, r in got.iterrows()}
    # b = 2v + 1 per row
    assert by[0] == (2 * 1.0 + 1) + (2 * 3.0 + 1) + (2 * 5.0 + 1)
    assert by[1] == (2 * 2.0 + 1) + (2 * 4.0 + 1)
