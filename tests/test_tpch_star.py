"""TPC-H star workload: join elimination + query parity (the reference's
TPCHTest analog, SURVEY.md §4).

Two assertion styles, mirroring upstream: (1) the rewrite happened — explain
output shows the collapsed fact scan (the "plan contains DruidQuery" check);
(2) exact/near-exact parity against a float64 pandas oracle on the same
generated rows."""

import numpy as np
import pandas as pd
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.workloads import tpch

SCALE = 0.004  # ~24k lineitem rows


@pytest.fixture(scope="module")
def ctx_tables():
    ctx = sd.TPUOlapContext()
    tables = tpch.register(ctx, scale=SCALE, rows_per_segment=8192)
    return ctx, tables


@pytest.fixture(scope="module")
def frame(ctx_tables):
    return tpch.flat_frame(ctx_tables[1])


def test_star_join_collapses(ctx_tables):
    """The rewrite collapses all dim joins onto the fact table (explain echoes
    the *logical* plan, which legitimately contains Join nodes — assert on the
    rewrite result, not the explain text)."""
    ctx, _ = ctx_tables
    rw = ctx.plan_sql(tpch.QUERIES["q5"])
    assert rw.datasource == "lineitem"
    assert rw.query.datasource == "lineitem"
    plan = ctx.explain(tpch.QUERIES["q5"])
    assert "Rewrite FAILED" not in plan, plan
    assert '"dataSource": "lineitem"' in plan, plan


def test_snowflake_customer_edge_collapses(ctx_tables):
    ctx, _ = ctx_tables
    rw = ctx.plan_sql(tpch.QUERIES["q3"])
    assert rw.datasource == "lineitem"
    plan = ctx.explain(tpch.QUERIES["q3"])
    assert "Rewrite FAILED" not in plan, plan


def test_q1_parity(ctx_tables, frame):
    ctx, _ = ctx_tables
    got = ctx.sql(tpch.QUERIES["q1"])
    want = tpch.oracle(frame, "q1")
    assert list(got["l_returnflag"]) == list(want["l_returnflag"])
    assert list(got["l_linestatus"]) == list(want["l_linestatus"])
    np.testing.assert_array_equal(got["count_order"], want["count_order"])
    for c in ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge"):
        np.testing.assert_allclose(got[c], want[c], rtol=2e-5)
    for c in ("avg_qty", "avg_price", "avg_disc"):
        np.testing.assert_allclose(got[c], want[c], rtol=2e-5)


def test_q3_parity_top10(ctx_tables, frame):
    ctx, _ = ctx_tables
    got = ctx.sql(tpch.QUERIES["q3"])
    want = tpch.oracle(frame, "q3")
    assert len(got) == len(want) == 10
    # revenue ordering may tie-break differently; compare the value sets
    np.testing.assert_allclose(
        np.sort(got["revenue"])[::-1], want["revenue"], rtol=2e-5
    )


def test_q5_parity(ctx_tables, frame):
    ctx, _ = ctx_tables
    got = ctx.sql(tpch.QUERIES["q5"]).sort_values("s_nation").reset_index(drop=True)
    want = tpch.oracle(frame, "q5").sort_values("s_nation").reset_index(drop=True)
    assert list(got["s_nation"]) == list(want["s_nation"])
    np.testing.assert_allclose(got["revenue"], want["revenue"], rtol=2e-5)


def test_q6_parity(ctx_tables, frame):
    ctx, _ = ctx_tables
    got = ctx.sql(tpch.QUERIES["q6"])
    want = tpch.oracle(frame, "q6")
    np.testing.assert_allclose(float(got["revenue"][0]), want, rtol=2e-5)


def test_q12_parity(ctx_tables, frame):
    ctx, _ = ctx_tables
    got = ctx.sql(tpch.QUERIES["q12"])
    want = tpch.oracle(frame, "q12")
    assert list(got["l_shipmode"]) == list(want["l_shipmode"])
    np.testing.assert_array_equal(got["high_line_count"], want["high_line_count"])
    np.testing.assert_array_equal(got["low_line_count"], want["low_line_count"])


def test_q8_parity(ctx_tables, frame):
    ctx, _ = ctx_tables
    got = ctx.sql(tpch.QUERIES["q8"])
    want = tpch.oracle(frame, "q8")
    assert len(got) == len(want)
    np.testing.assert_array_equal(
        np.asarray(got["o_orderdate_year"], dtype=np.int64),
        np.asarray(want["o_orderdate_year"], dtype=np.int64),
    )
    np.testing.assert_allclose(got["brazil_volume"], want["brazil_volume"], rtol=2e-5)
    np.testing.assert_allclose(got["total_volume"], want["total_volume"], rtol=2e-5)


def test_q8_extract_year_parity(ctx_tables, frame):
    """EXTRACT(YEAR FROM o_orderdate) in GROUP BY plans as a dictionary-
    backed dimension (VERDICT r1 missing #7) — no pre-materialized year
    column; results must match the q8 oracle exactly."""
    ctx, _ = ctx_tables
    got = ctx.sql(tpch.QUERIES["q8_extract"])
    want = tpch.oracle(frame, "q8")
    assert len(got) == len(want)
    np.testing.assert_array_equal(
        np.asarray(got["o_orderdate_year"], dtype=np.int64),
        np.asarray(want["o_orderdate_year"], dtype=np.int64),
    )
    np.testing.assert_allclose(got["brazil_volume"], want["brazil_volume"], rtol=2e-5)
    np.testing.assert_allclose(got["total_volume"], want["total_volume"], rtol=2e-5)


def test_q7_parity(ctx_tables, frame):
    """OR-of-ANDs across two dimension branches + EXTRACT over the fact's
    own time column as a grouping dimension."""
    ctx, _ = ctx_tables
    got = ctx.sql(tpch.QUERIES["q7"])
    want = tpch.oracle(frame, "q7")
    keys = ["s_nation", "c_nation", "l_year"]
    got = got.sort_values(keys).reset_index(drop=True)
    want = want.sort_values(keys).reset_index(drop=True)
    assert len(got) == len(want)
    for k in ("s_nation", "c_nation"):
        assert list(got[k]) == list(want[k])
    np.testing.assert_array_equal(
        np.asarray(got["l_year"], dtype=np.int64),
        np.asarray(want["l_year"], dtype=np.int64),
    )
    np.testing.assert_allclose(got["revenue"], want["revenue"], rtol=2e-5)


def test_q14_parity(ctx_tables, frame):
    """LIKE inside CASE + ratio of two aggregates as a post-aggregation."""
    ctx, _ = ctx_tables
    got = ctx.sql(tpch.QUERIES["q14"])
    want = tpch.oracle(frame, "q14")
    np.testing.assert_allclose(float(got["promo_revenue"][0]), want, rtol=2e-5)


def test_q19_parity(ctx_tables, frame):
    """Disjunction of conjunct blocks mixing string dims and metric bounds."""
    ctx, _ = ctx_tables
    got = ctx.sql(tpch.QUERIES["q19"])
    want = tpch.oracle(frame, "q19")
    np.testing.assert_allclose(float(got["revenue"][0]), want, rtol=2e-5)


def test_q3_uses_sparse_path(ctx_tables):
    """l_orderkey grouping has a huge domain — confirm the sparse
    accelerator actually answered it (not the scatter fallback)."""
    ctx, _ = ctx_tables
    eng = ctx.engine
    ctx.sql(tpch.QUERIES["q3"])
    assert not any(
        "lineitem" in k[0] and "l_orderkey" in k[0] for k in eng._sparse_disabled
    )


def test_q10_parity_fd_pruning(ctx_tables, frame):
    """Q10: GROUP BY c_custkey, c_name, c_nation — the declared functional
    dependencies (c_custkey -> c_name/c_nation) must prune the dependent
    columns from the kernel grouping (hidden code-max carriers), keeping the
    group domain at |custkey| instead of the cardinality product."""
    ctx, tables = ctx_tables
    rw = ctx.plan_sql(tpch.QUERIES["q10"])
    assert rw.fd_restores, "FD pruning did not engage"
    restored = {r[0] for r in rw.fd_restores}
    assert restored == {"c_name", "c_nation"}
    kernel_dims = {d.name for d in rw.query.dimensions} if hasattr(
        rw.query, "dimensions"
    ) else {rw.query.dimension.name}
    assert "c_name" not in kernel_dims and "c_nation" not in kernel_dims

    got = ctx.sql(tpch.QUERIES["q10"]).reset_index(drop=True)
    want = tpch.oracle(frame, "q10")
    assert list(got.columns)[:4] == ["c_custkey", "c_name", "c_nation", "revenue"]
    assert len(got) == len(want)
    np.testing.assert_allclose(
        got["revenue"].astype(float), want["revenue"], rtol=2e-5
    )
    # ties in revenue could reorder rows; compare as sets of customers
    assert set(got["c_custkey"].astype(int)) == set(
        want["c_custkey"].astype(int)
    )
    # restored attribute values are consistent with the source table
    cust = tables["customer"]
    for _, row in got.iterrows():
        k = int(row["c_custkey"])
        assert row["c_name"] == cust["c_name"][k]
        assert row["c_nation"] == cust["c_nation"][k]


def test_fd_pruning_respects_order_by_and_cube(ctx_tables, frame):
    """A column referenced by the device-side ORDER BY must not be pruned;
    grouping-set queries skip pruning entirely (set indices reference the
    full dimension list)."""
    ctx, _ = ctx_tables
    sql = (
        "SELECT c_custkey, c_name, sum(l_extendedprice) AS s "
        "FROM lineitem JOIN orders ON l_orderkey = o_orderkey "
        "JOIN customer ON o_custkey = c_custkey "
        "GROUP BY c_custkey, c_name ORDER BY c_name LIMIT 5"
    )
    rw = ctx.plan_sql(sql)
    pruned = {r[0] for r in rw.fd_restores}
    assert "c_name" not in pruned
    got = ctx.sql(sql)
    assert list(got["c_name"]) == sorted(got["c_name"])

    cube = (
        "SELECT c_custkey, c_name, count(*) AS n "
        "FROM lineitem JOIN orders ON l_orderkey = o_orderkey "
        "JOIN customer ON o_custkey = c_custkey "
        "GROUP BY CUBE (c_custkey, c_name)"
    )
    rw2 = ctx.plan_sql(cube)
    assert rw2.fd_restores == ()
