"""Exact COUNT(DISTINCT) (count_distinct_mode="exact") and SELECT DISTINCT.

Reference parity: pushHLLTODruid=false kept COUNT(DISTINCT) exact by letting
Spark finish the distinct after the Druid scan (SURVEY.md §2 DefaultSource
options row); here the planner's two-phase rewrite groups by (dims, x) on
device and re-aggregates on host.  SELECT DISTINCT is the Catalyst
Distinct -> Aggregate rewrite."""

import numpy as np
import pandas as pd
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.config import SessionConfig
from spark_druid_olap_tpu.plan.planner import RewriteError


@pytest.fixture(scope="module")
def data():
    n = 30_000
    rng = np.random.default_rng(17)
    return {
        "region": rng.choice(
            np.array(["EU", "US", "APAC"], dtype=object), n
        ),
        "city": rng.choice(
            np.array([f"c{i}" for i in range(200)], dtype=object), n
        ),
        "user": rng.choice(
            np.array([f"u{i}" for i in range(5_000)], dtype=object), n
        ),
        "v": rng.random(n).astype(np.float32),
    }


@pytest.fixture(scope="module")
def exact_ctx(data):
    ctx = sd.TPUOlapContext(SessionConfig(count_distinct_mode="exact"))
    ctx.register_table(
        "ev", data, dimensions=["region", "city", "user"], metrics=["v"]
    )
    return ctx


@pytest.fixture(scope="module")
def frame(data):
    return pd.DataFrame({k: np.asarray(v) for k, v in data.items()})


def test_exact_global_count_distinct(exact_ctx, frame):
    got = exact_ctx.sql("SELECT count(DISTINCT user) AS u FROM ev")
    assert int(got["u"][0]) == frame["user"].nunique()


def test_exact_grouped_with_other_aggs(exact_ctx, frame):
    got = exact_ctx.sql(
        "SELECT region, count(DISTINCT city) AS cities, sum(v) AS total, "
        "count(*) AS n, avg(v) AS mean FROM ev GROUP BY region "
        "ORDER BY region"
    )
    want = (
        frame.groupby("region", as_index=False)
        .agg(
            cities=("city", "nunique"),
            total=("v", lambda s: s.astype(np.float64).sum()),
            n=("v", "count"),
            mean=("v", lambda s: s.astype(np.float64).mean()),
        )
        .sort_values("region")
        .reset_index(drop=True)
    )
    assert list(got["region"]) == list(want["region"])
    np.testing.assert_array_equal(got["cities"], want["cities"])
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["total"], want["total"], rtol=2e-5)
    np.testing.assert_allclose(got["mean"], want["mean"], rtol=2e-5)


def test_exact_two_distincts_with_filter_and_having(exact_ctx, frame):
    got = exact_ctx.sql(
        "SELECT region, count(DISTINCT city) AS c, count(DISTINCT user) AS u "
        "FROM ev WHERE city <> 'c0' GROUP BY region "
        "HAVING count(DISTINCT city) > 0 ORDER BY u DESC LIMIT 2"
    )
    f = frame[frame.city != "c0"]
    want = (
        f.groupby("region", as_index=False)
        .agg(c=("city", "nunique"), u=("user", "nunique"))
        .sort_values("u", ascending=False)
        .head(2)
        .reset_index(drop=True)
    )
    assert list(got["region"]) == list(want["region"])
    np.testing.assert_array_equal(got["c"], want["c"])
    np.testing.assert_array_equal(got["u"], want["u"])


def test_exact_distinct_is_exact_where_sketch_is_not(data, frame):
    """The point of the mode: HLL at default precision has ~1% error at 5k
    distinct; exact mode must equal the true count."""
    approx_ctx = sd.TPUOlapContext()  # default: approx
    approx_ctx.register_table(
        "ev", data, dimensions=["region", "city", "user"], metrics=["v"]
    )
    approx = int(
        approx_ctx.sql("SELECT count(DISTINCT user) AS u FROM ev")["u"][0]
    )
    true = frame["user"].nunique()
    assert abs(approx - true) / true < 0.05  # sketch: close
    # exact: equal (test above), and the two modes really took different paths
    rw = sd.TPUOlapContext(
        SessionConfig(count_distinct_mode="exact")
    )
    rw.register_table("ev", data, dimensions=["region", "city", "user"])
    assert rw.plan_sql("SELECT count(DISTINCT user) AS u FROM ev").exact_distinct is not None
    assert approx_ctx.plan_sql("SELECT count(DISTINCT user) AS u FROM ev").exact_distinct is None


def test_exact_rejects_mix_with_approx(exact_ctx):
    with pytest.raises(RewriteError, match="mix exact"):
        exact_ctx.plan_sql(
            "SELECT count(DISTINCT city) AS c, "
            "approx_count_distinct(user) AS u FROM ev"
        )


def test_select_distinct(exact_ctx, frame):
    got = exact_ctx.sql("SELECT DISTINCT region FROM ev ORDER BY region")
    want = sorted(frame["region"].unique())
    assert list(got["region"]) == want


def test_select_distinct_two_cols(exact_ctx, frame):
    got = exact_ctx.sql("SELECT DISTINCT region, city FROM ev")
    want = frame[["region", "city"]].drop_duplicates()
    assert len(got) == len(want)
    gs = set(zip(got["region"], got["city"]))
    ws = set(zip(want["region"], want["city"]))
    assert gs == ws


def test_sum_distinct_refused_both_modes(exact_ctx, data):
    """SUM(DISTINCT)/AVG(DISTINCT) cannot be pushed down without silently
    double-counting — both modes must refuse, never return wrong data."""
    approx_ctx = sd.TPUOlapContext()
    approx_ctx.register_table(
        "ev", data, dimensions=["region", "city", "user"], metrics=["v"]
    )
    for c in (exact_ctx, approx_ctx):
        with pytest.raises(RewriteError):
            c.plan_sql("SELECT region, count(DISTINCT city) AS d, sum(DISTINCT v) AS s FROM ev GROUP BY region") \
                if c is exact_ctx else c.plan_sql("SELECT sum(DISTINCT v) AS s FROM ev")


def test_exact_mode_output_order_matches_approx(exact_ctx, data):
    """Column order must not depend on count_distinct_mode."""
    approx_ctx = sd.TPUOlapContext()
    approx_ctx.register_table(
        "ev", data, dimensions=["region", "city", "user"], metrics=["v"]
    )
    sql = ("SELECT region, count(DISTINCT city) AS d, sum(v) AS s "
           "FROM ev GROUP BY region ORDER BY region")
    a = exact_ctx.sql(sql)
    b = approx_ctx.sql(sql)
    assert list(a.columns) == list(b.columns) == ["region", "d", "s"]
