"""Anytime answers (ISSUE 7): deadline-bounded partial results.

Layers under test:

1. `PartialCollector` / `partial_scope` / `checkpoint_partial` unit
   semantics (coverage math, pass reset, fallback accumulation, the
   disabled-scope opt-out occupying the contextvar).
2. Engine-level partials: an injected deadline pinned to the K-th
   segment checkpoint yields a coverage-stamped best-effort answer with
   the result-cache kept clean.
3. The SSB-13 deadline-sweep acceptance: at 100% device failure plus a
   deadline expiring mid-(fallback)-scan, every query answers with
   monotonically-growing coverage as the deadline loosens, never an
   error, and coverage=1.0 answers equal the oracle exactly.
4. Concurrent hammer: streamed appends racing deadline-partial count
   queries — the partial count must equal rows_seen exactly (delta rows
   can never be double-counted in coverage accounting).
5. The emit-only OTLP export flag (ROADMAP obs follow-up (d)).
"""

import json
import threading

import numpy as np
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.config import SessionConfig
from spark_druid_olap_tpu.resilience import (
    DeadlineExceeded,
    InjectedDeadline,
    PartialCollector,
    checkpoint,
    checkpoint_partial,
    current_partial,
    deadline_scope,
    injector,
    partial_scope,
)
from spark_druid_olap_tpu.utils.floatcmp import frames_allclose
from spark_druid_olap_tpu.workloads import ssb


@pytest.fixture(autouse=True)
def _clean_injector():
    injector().disarm()
    yield
    injector().disarm()


def _ctx(**overrides):
    cfg = SessionConfig.load_calibrated()
    cfg.result_cache_entries = 0
    cfg.retry_backoff_ms = 1.0
    # pin the single-device executors: the conftest's 8-device CPU mesh
    # would route these queries to the distributed engine, whose
    # deadline behavior is drain-to-complete, not segment-loop partials
    cfg.prefer_distributed = False
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return sd.TPUOlapContext(cfg)


def _flat_table(ctx, n=20_000, segment_rows=1 << 10, name="t"):
    ctx.register_table(
        name,
        {
            "d": np.array(["a", "b", "c", "d"] * (n // 4), dtype=object),
            "v": np.ones(n, dtype=np.float32),
        },
        dimensions=["d"],
        metrics=["v"],
        rows_per_segment=segment_rows,
    )
    return n


# ---------------------------------------------------------------------------
# 1. collector semantics
# ---------------------------------------------------------------------------


def test_collector_coverage_math():
    pc = PartialCollector()
    pc.add_scope(4, 1000, delta_rows=100)
    pc.add_seen(2, 400, delta_rows=100)
    assert pc.coverage() == 0.4
    assert not pc.is_partial  # not triggered yet
    pc.trigger("x")
    assert pc.is_partial
    d = pc.to_dict()
    assert d["partial"] is True and d["site"] == "x"
    assert d["delta_rows_seen"] == 100 and d["rows_total"] == 1000


def test_collector_complete_drain_is_not_partial():
    """A trigger observed after every batch dispatched drains to the
    complete answer: coverage 1.0, is_partial False."""
    pc = PartialCollector()
    pc.add_scope(2, 100)
    pc.add_seen(2, 100)
    pc.trigger("engine.resolve")
    assert pc.coverage() == 1.0
    assert not pc.is_partial


def test_collector_declared_empty_scope_is_complete():
    """A DECLARED zero-row scope (every segment pruned, or a presence
    pass proving no group survives) is complete by vacuity: coverage
    1.0, never partial — unlike an UNDECLARED scope, which must claim
    nothing."""
    pc = PartialCollector()
    pc.trigger("engine.resolve")
    assert pc.coverage() is None and pc.is_partial  # undeclared
    pc2 = PartialCollector()
    pc2.begin_pass()
    pc2.add_scope(0, 0)
    pc2.trigger("engine.resolve")
    assert pc2.coverage() == 1.0
    assert not pc2.is_partial
    # begin_pass resets the declaration along with the counters
    pc2.begin_pass()
    assert pc2.coverage() is None


def test_collector_begin_pass_resets_unless_fallback_owned():
    pc = PartialCollector()
    pc.add_scope(4, 1000)
    pc.begin_pass()
    assert pc.to_dict()["rows_total"] == 0
    pc.in_fallback = True
    pc.add_scope(4, 1000)
    pc.begin_pass()  # assist subtrees must not reset the interpreter
    assert pc.to_dict()["rows_total"] == 1000


def test_partial_scope_outermost_wins_and_optout_occupies():
    with partial_scope(True) as outer:
        with partial_scope(False) as inner:
            assert inner is outer  # joined, not replaced
    # an explicit opt-out occupies the scope: inner defaults cannot re-arm
    with partial_scope(False):
        assert current_partial() is None
        with partial_scope(True):
            assert current_partial() is None


def test_checkpoint_partial_trigger_and_drain():
    with partial_scope(True) as pc, deadline_scope(0.0001):
        import time

        time.sleep(0.001)  # the deadline is now expired
        assert checkpoint_partial("site.a") is True
        assert pc.triggered and pc.triggered_site == "site.a"
        # drained: plain checkpoints are no-ops now, never raises
        checkpoint("site.b")
        assert checkpoint_partial("site.c") is True


def test_checkpoint_partial_without_collector_raises():
    with deadline_scope(0.0001):
        import time

        time.sleep(0.001)
        with pytest.raises(DeadlineExceeded):
            checkpoint_partial("site.a")


def test_injected_deadline_skip_is_deterministic():
    injector().arm(
        "s", "error", times=1, skip=2, error_type=InjectedDeadline
    )
    checkpoint("s")
    checkpoint("s")
    with partial_scope(True) as pc:
        assert checkpoint_partial("s") is True
    assert pc.triggered_site == "s"


# ---------------------------------------------------------------------------
# 2. engine partials
# ---------------------------------------------------------------------------


def test_engine_partial_coverage_and_attrs():
    ctx = _ctx()
    n = _flat_table(ctx)
    oracle = ctx.sql("SELECT d, sum(v) AS s FROM t GROUP BY d")
    injector().arm(
        "engine.segment_loop", "error", times=1, skip=2,
        error_type=InjectedDeadline,
    )
    got = ctx.sql("SELECT d, sum(v) AS s FROM t GROUP BY d")
    m = ctx.last_metrics
    assert m.partial is True
    assert 0.0 < m.coverage < 1.0
    assert m.rows_seen == got["s"].sum()  # v == 1: the sum IS rows seen
    assert got.attrs["partial"] is True
    assert got.attrs["coverage"] == m.coverage
    # and the answer is a true subset: per-group partial <= oracle
    merged = oracle.merge(got, on="d", suffixes=("_full", "_part"))
    assert (merged["s_part"] <= merged["s_full"]).all()


def test_partial_zero_coverage_is_well_formed():
    ctx = _ctx()
    _flat_table(ctx)
    injector().arm(
        "engine.segment_loop", "error", times=1,
        error_type=InjectedDeadline,
    )
    got = ctx.sql("SELECT d, sum(v) AS s FROM t GROUP BY d")
    assert ctx.last_metrics.partial and ctx.last_metrics.coverage == 0.0
    assert list(got.columns) == ["d", "s"]  # well-formed, empty groups
    assert len(got) == 0


def test_pruned_empty_scope_not_flagged_partial():
    """Every segment interval-pruned: the exact answer is the empty
    frame, and a deadline trigger later in the lifecycle (engine.resolve)
    must not flag it partial with an unknown denominator."""
    ctx = _ctx()
    n = 20_000
    DAY = 86_400_000
    ctx.register_table(
        "tt",
        {
            "d": np.array(["a", "b"] * (n // 2), dtype=object),
            "v": np.ones(n, dtype=np.float32),
            "ts": (np.arange(n) % 10 * DAY).astype(np.int64),
        },
        dimensions=["d"], metrics=["v"], time_column="ts",
        rows_per_segment=1 << 10,
    )
    q = f"SELECT d, sum(v) AS s FROM tt WHERE ts >= {100 * DAY} GROUP BY d"
    injector().arm(
        "engine.resolve", "error", times=1, error_type=InjectedDeadline
    )
    got = ctx.sql(q)
    m = ctx.last_metrics
    assert len(got) == 0
    assert not m.partial  # complete by vacuity, not a best-effort answer


def test_adaptive_empty_kept_set_not_flagged_partial():
    """The adaptive presence pass proving NO group survives the filter
    yields the exact empty frame — an expiry observed afterwards must
    stamp it complete (the q3_4 SSB shape: both filter values exist in
    their dictionaries but never co-occur on a row)."""
    ctx = _ctx()
    n = 40_000
    i = np.arange(n) % 200  # diagonal pairing: a_i only ever with b_i
    ctx.register_table(
        "hg",
        {
            "a": np.array([f"a{k:03d}" for k in i], dtype=object),
            "b": np.array([f"b{k:03d}" for k in i], dtype=object),
            "v": np.ones(n, dtype=np.float32),
        },
        dimensions=["a", "b"], metrics=["v"], rows_per_segment=1 << 12,
    )
    q = (
        "SELECT a, b, sum(v) AS s FROM hg "
        "WHERE a = 'a000' AND b = 'b001' GROUP BY a, b"
    )
    full = ctx.sql(q)
    assert len(full) == 0 and ctx.last_metrics.strategy == "adaptive"
    injector().arm(
        "engine.resolve", "error", times=1, error_type=InjectedDeadline
    )
    got = ctx.sql(q)
    m = ctx.last_metrics
    assert len(got) == 0 and m.strategy == "adaptive"
    assert not m.partial


def test_sparse_overflow_during_drain_declines_without_error_pin():
    """A partial drain that stops the sparse segment loop can leave the
    merged state overflowed; the slot/row ladder must NOT re-dispatch
    the already-stopped scope (dispatch would return None and crash the
    fetch) — it declines un-error-counted, so a deadline can never pin
    the query shape off the sparse tier."""
    from spark_druid_olap_tpu.catalog.segment import (
        DimensionDict,
        build_datasource,
    )
    from spark_druid_olap_tpu.exec.engine import Engine
    from spark_druid_olap_tpu.models.aggregations import Count, DoubleSum
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec
    from spark_druid_olap_tpu.models.query import GroupByQuery

    n, da, db = 40_000, 300, 300  # >4096 distinct pairs per batch
    rng = np.random.default_rng(11)
    cols = {
        "a": rng.integers(0, da, n),
        "b": rng.integers(0, db, n),
        "v": np.ones(n, np.float32),
    }
    ds = build_datasource(
        "hc_drain", cols, dimension_cols=["a", "b"], metric_cols=["v"],
        dicts={
            "a": DimensionDict(values=tuple(range(da))),
            "b": DimensionDict(values=tuple(range(db))),
        },
        rows_per_segment=1 << 12,
    )
    eng = Engine(strategy="sparse")
    q = GroupByQuery(
        datasource="hc_drain",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(Count("n"), DoubleSum("s", "v")),
    )
    injector().arm(
        "sparse.segment_loop", "error", times=1, skip=1,
        error_type=InjectedDeadline,
    )
    with partial_scope(True) as pc:
        got = eng.execute(q, ds)  # must not raise
    assert pc.triggered and pc.is_partial
    assert set(got.columns) == {"a", "b", "n", "s"}
    # declined, never error-counted: no pin bookkeeping was touched
    assert not eng._sparse_error_counts
    assert not eng._sparse_disabled


def test_partial_never_enters_result_cache():
    ctx = _ctx(result_cache_entries=16)
    _flat_table(ctx)
    q = "SELECT d, sum(v) AS s FROM t GROUP BY d"
    injector().arm(
        "engine.segment_loop", "error", times=1, skip=2,
        error_type=InjectedDeadline,
    )
    part = ctx.sql(q)
    assert ctx.last_metrics.partial
    # the rerun (no fault) must compute the EXACT answer, not serve the
    # truncated frame back from the result cache
    full = ctx.sql(q)
    assert not ctx.last_metrics.partial
    assert full["s"].sum() > part["s"].sum()
    assert full["s"].sum() == 20_000
    # and the exact answer IS cached (third run hits)
    ctx.sql(q)
    assert ctx.last_metrics.strategy == "result-cache"


def test_partial_coverage_histogram_published():
    from spark_druid_olap_tpu.obs import get_registry

    ctx = _ctx()
    _flat_table(ctx)
    before = get_registry().counter(
        "sdol_partial_results_total",
        labels=("site",),
    ).snapshot()
    injector().arm(
        "engine.segment_loop", "error", times=1, skip=1,
        error_type=InjectedDeadline,
    )
    ctx.sql("SELECT d, sum(v) AS s FROM t GROUP BY d")
    after = get_registry().counter(
        "sdol_partial_results_total", labels=("site",)
    ).snapshot()
    assert sum(after.values()) == sum(before.values()) + 1


def test_partial_span_recorded_in_trace():
    ctx = _ctx()
    _flat_table(ctx)
    injector().arm(
        "engine.segment_loop", "error", times=1, skip=1,
        error_type=InjectedDeadline,
    )
    ctx.sql("SELECT d, sum(v) AS s FROM t GROUP BY d")
    doc = ctx.tracer.last_trace_dict()

    def names(node):
        out = [node["name"]]
        for c in node.get("children", ()):
            out.extend(names(c))
        return out

    assert "partial" in names(doc["spans"])


def test_scan_partial_returns_row_prefix():
    ctx = _ctx()
    _flat_table(ctx)
    injector().arm(
        "engine.scan_loop", "error", times=1, skip=3,
        error_type=InjectedDeadline,
    )
    got = ctx.sql("SELECT d, v FROM t")
    m = ctx.last_metrics
    assert 0 < len(got) < 20_000
    pc_cov = got.attrs.get("coverage")
    assert pc_cov is not None and 0 < pc_cov < 1


# ---------------------------------------------------------------------------
# 3. SSB-13 deadline-sweep acceptance
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ssb_tables():
    return ssb.gen_tables(scale=0.01, seed=7)


def _clear_fallback_frames(ctx):
    # the fallback's frame LRU would serve fully-decoded tables across
    # sweep points, decoupling the skip index from decode progress
    if hasattr(ctx.catalog, "_fallback_frames"):
        ctx.catalog._fallback_frames.clear()


def test_ssb13_deadline_sweep_monotone_coverage(ssb_tables):
    """The acceptance gate: 100% device failure AND a deadline expiring
    mid-scan.  Every SSB query at every deadline returns a well-formed
    answer with a coverage fraction; loosening the deadline (expiry
    pinned to later checkpoints) never shrinks coverage; coverage=1.0
    answers equal the oracle exactly."""
    ctx = _ctx()
    ssb.register(ctx, tables=ssb_tables, rows_per_segment=1 << 13)
    oracle = {}
    for name, q in ssb.QUERIES.items():
        oracle[name] = ctx.sql(q)
        assert ctx.last_metrics.executor == "device", name

    injector().arm("device_dispatch", "error")  # 100% device failure
    sweep = (0, 1, 3, 6, 12, 10_000)  # expiry at the k-th decode step
    coverages = {name: [] for name in ssb.QUERIES}
    for k in sweep:
        for name, q in ssb.QUERIES.items():
            _clear_fallback_frames(ctx)
            injector().arm(
                "fallback.decode", "error", times=1, skip=k,
                error_type=InjectedDeadline,
            )
            got = ctx.sql(q)  # must NEVER raise
            m = ctx.last_metrics
            # a query whose scope zone-map-prunes to zero segments never
            # dispatches, so it legitimately "succeeds on device" even
            # at 100% dispatch failure; everything else must degrade
            if m.executor == "device":
                assert m.rows_scanned == 0, name
            else:
                assert m.executor in ("fallback", "device+fallback"), name
            cov = m.coverage if m.partial else 1.0
            assert cov is not None and 0.0 <= cov <= 1.0, (name, k)
            coverages[name].append(cov)
            if cov == 1.0:
                ok, msg = frames_allclose(got, oracle[name])
                assert ok, f"{name}@skip={k}: {msg}"
            injector().disarm("fallback.decode")
    for name, cs in coverages.items():
        assert all(
            a <= b + 1e-9 for a, b in zip(cs, cs[1:])
        ), f"{name}: coverage not monotone over the sweep: {cs}"
        assert cs[-1] == 1.0, f"{name}: loosest deadline must be exact"


def test_interp_expiry_drain_reports_honest_coverage(monkeypatch):
    """Regression: the drain-rerun after an interpreter-level expiry
    must reset the collector's accounting (api._run_fallback) and may
    only serve segments still warm in the decode cache (decoded_frame
    drain mode).  Before the fix the aborted pass's counters doubled
    the denominator and claimed rows the rerun never aggregated — an
    answer over ZERO rows could ship stamped coverage≈0.5.  Invariant:
    a partial COUNT(*) totals exactly rows_seen."""
    from spark_druid_olap_tpu.exec import fallback as fb

    # frame cache off: the whole-table LRU would mask the rerun's decode
    monkeypatch.setattr(fb, "_FRAME_CACHE_MAX_ROWS", -1)
    monkeypatch.setattr(fb, "_decode_cache", None)
    n = 1 << 12
    sql = (
        "SELECT COUNT(*) AS c FROM a "
        "UNION ALL SELECT COUNT(*) AS c FROM b"
    )
    saw_mid_coverage = False
    for k in range(8):  # expiry pinned to the k-th interpreter node
        fb._decode_cache = None  # cold decode cache per sweep point
        ctx = _ctx(partial_results=True)
        _flat_table(ctx, n=n, name="a")
        _flat_table(ctx, n=n, name="b")
        injector().arm(
            "fallback.interp", "error", times=1, skip=k,
            error_type=InjectedDeadline,
        )
        df = ctx.sql(sql)  # set-op: fallback-only; must never raise
        m = ctx.last_metrics
        total = int(df["c"].sum()) if len(df) else 0
        if m.partial:
            assert total == m.rows_seen, (k, total, m.rows_seen)
            assert m.coverage is not None and 0.0 <= m.coverage <= 1.0
            if 0.0 < m.coverage < 1.0:
                saw_mid_coverage = True
        else:
            assert total == 2 * n, k  # drained to the exact answer
    assert saw_mid_coverage, (
        "sweep never exercised the expiry-after-one-table drain"
    )


def test_half_open_probe_on_sparse_strategy_query_stays_degraded(ssb_tables):
    """Regression: the sparse tier dispatches to the device, so it must
    pass the `device_dispatch` fault site exactly like the dense engine
    (engine.py) — before the fix it did not, and at "100% device
    failure" a breaker half-open probe routed to a sparse-strategy query
    silently succeeded on the dead device, closed the breaker, and later
    queries ran on-device (breaking the deadline-sweep premise whenever
    the 2s cooldown elapsed mid-run).  The probe must fail, the query
    must still degrade, and the breaker must re-open."""
    ctx = _ctx()
    ssb.register(ctx, tables=ssb_tables, rows_per_segment=1 << 13)
    q = ssb.QUERIES["q4_3"]  # lands on the sparse strategy at this scale
    oracle = ctx.sql(q)
    assert ctx.last_metrics.executor == "device"

    injector().arm("device_dispatch", "error")  # 100% device failure
    br = ctx.resilience.breaker_for("device")
    for _ in range(10):
        ctx.sql(q)  # degrades; consecutive failures open the breaker
        if br.state == "open":
            break
    assert br.state == "open"
    # rewind the open timestamp: the cooldown has "elapsed", so the next
    # allow() admits exactly one half-open probe, which the engine routes
    # to the same (sparse) strategy as the warm run
    br._opened_at -= (br.cooldown_ms / 1e3) + 0.01
    assert br.state == "half_open"
    got = ctx.sql(q)
    m = ctx.last_metrics
    assert m.executor in ("fallback", "device+fallback"), (
        "half-open probe must not succeed on the dead device "
        f"(executor={m.executor}, strategy={m.strategy})"
    )
    assert br.state == "open", "the failed probe must re-open the breaker"
    ok, msg = frames_allclose(got, oracle)
    assert ok, msg


# ---------------------------------------------------------------------------
# 4. appends racing deadline-partial queries
# ---------------------------------------------------------------------------


def test_hammer_appends_vs_partial_queries_never_double_count():
    """Streamed appends race deadline-partial count queries.  The
    invariant that catches double-counted delta rows exactly: a partial
    COUNT(*) equals rows_seen (every row the coverage accounting claims
    was seen is counted exactly once), and delta_rows_seen never exceeds
    the rows appended so far."""
    ctx = _ctx()
    n0 = _flat_table(ctx, n=8_192, segment_rows=1 << 10)
    stop = threading.Event()
    appended = {"rows": 0}
    batch = 256

    def appender():
        while not stop.is_set():
            ctx.append_rows(
                "t",
                {
                    "d": np.array(["a", "b"] * (batch // 2), dtype=object),
                    "v": np.ones(batch, dtype=np.float32),
                },
            )
            appended["rows"] += batch

    th = threading.Thread(target=appender, daemon=True)
    th.start()
    try:
        for i in range(30):
            injector().arm(
                "engine.segment_loop", "error", times=1, skip=i % 7,
                error_type=InjectedDeadline,
            )
            got = ctx.sql("SELECT count(*) AS n FROM t")
            m = ctx.last_metrics
            count = int(got["n"][0]) if len(got) else 0
            if m.partial:
                assert count == m.rows_seen, (i, count, m.rows_seen)
                assert 0.0 <= m.coverage <= 1.0
                # delta rows are seen at most once, and only ones that
                # were actually appended by the time the snapshot ran
                assert m.delta_rows_seen <= appended["rows"] + n0
            else:
                # complete answers count exactly what their snapshot held
                assert count >= n0
            injector().disarm("engine.segment_loop")
    finally:
        stop.set()
        th.join(timeout=10)
    # quiesced final answer is exact
    injector().disarm()
    got = ctx.sql("SELECT count(*) AS n FROM t")
    assert int(got["n"][0]) == n0 + appended["rows"]
    assert not ctx.last_metrics.partial


# ---------------------------------------------------------------------------
# 5. OTLP export stub
# ---------------------------------------------------------------------------


def test_otlp_export_writes_resource_spans(tmp_path):
    path = str(tmp_path / "spans.otlp.jsonl")
    ctx = _ctx(otlp_export_path=path)
    _flat_table(ctx, n=2_000, segment_rows=1 << 10)
    ctx.sql("SELECT d, sum(v) AS s FROM t GROUP BY d")
    lines = [
        json.loads(x)
        for x in open(path, encoding="utf-8").read().splitlines()
    ]
    assert lines, "the flag must produce one OTLP line per finished trace"
    doc = lines[-1]
    spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
    names = {s["name"] for s in spans}
    assert "query" in names and "execute" in names
    root = next(s for s in spans if s["name"] == "query")
    assert len(root["traceId"]) == 32 and len(root["spanId"]) == 16
    children = [s for s in spans if s.get("parentSpanId")]
    assert children, "child spans must carry parentSpanId"
    for s in spans:
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])


def test_otlp_mapping_is_deterministic():
    from spark_druid_olap_tpu.obs.otlp import trace_to_otlp

    doc = {
        "query_id": "q-1",
        "query_type": "sql",
        "total_ms": 5.0,
        "spans": {
            "name": "query",
            "start_ms": 0.0,
            "duration_ms": 5.0,
            "children": [
                {
                    "name": "plan",
                    "start_ms": 1.0,
                    "duration_ms": 2.0,
                    "events": [
                        {"name": "breaker_state", "at_ms": 1.5,
                         "attrs": {"state": "closed"}}
                    ],
                }
            ],
        },
    }
    a = trace_to_otlp(doc, epoch_ns=1_000_000)
    b = trace_to_otlp(doc, epoch_ns=1_000_000)
    assert a == b
    spans = a["resourceSpans"][0]["scopeSpans"][0]["spans"]
    plan = next(s for s in spans if s["name"] == "plan")
    assert plan["parentSpanId"] == next(
        s for s in spans if s["name"] == "query"
    )["spanId"]
    assert plan["events"][0]["name"] == "breaker_state"
