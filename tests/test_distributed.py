"""Distributed (8-device CPU mesh) execution parity vs the local engine.

The multi-chip contract (SURVEY.md §4 implication #3): sharded execution with
ICI-collective merge must produce the same results as single-device — exact
for counts/min/max/sketch states, tight rtol for float sums (different
reduction grouping)."""

import jax
import numpy as np
import pytest

from spark_druid_olap_tpu.exec.engine import Engine
from spark_druid_olap_tpu.models.aggregations import (
    Count,
    DoubleMax,
    DoubleMin,
    DoubleSum,
    ExpressionAgg,
    HyperUnique,
    ThetaSketch,
)
from spark_druid_olap_tpu.models.dimensions import DimensionSpec
from spark_druid_olap_tpu.models.filters import Bound, Selector
from spark_druid_olap_tpu.models.query import GroupByQuery, TopNQuery
from spark_druid_olap_tpu.parallel.distributed import DistributedEngine
from spark_druid_olap_tpu.parallel.mesh import make_mesh
from spark_druid_olap_tpu.plan.expr import col


@pytest.fixture(scope="module")
def dist8():
    assert len(jax.devices()) >= 8, "conftest must provide 8 CPU devices"
    return DistributedEngine(mesh=make_mesh(n_data=8))


@pytest.fixture(scope="module")
def dist4x2():
    return DistributedEngine(mesh=make_mesh(n_data=4, n_groups=2))


def _q1():
    return GroupByQuery(
        datasource="tpch",
        dimensions=(
            DimensionSpec("l_returnflag"),
            DimensionSpec("l_linestatus"),
        ),
        aggregations=(
            DoubleSum("sum_qty", "l_quantity"),
            ExpressionAgg(
                "sum_disc_price",
                col("l_extendedprice") * (1 - col("l_discount")),
            ),
            DoubleMin("min_p", "l_extendedprice"),
            DoubleMax("max_p", "l_extendedprice"),
            Count("n"),
        ),
        filter=Selector("l_linestatus", "F"),
    )


def _check_against_local(dist, q, ds):
    got = dist.execute(q, ds)
    want = Engine().execute(q, ds)
    key = [d.name for d in q.dimensions] if isinstance(q, GroupByQuery) else None
    if key:
        got = got.sort_values(key).reset_index(drop=True)
        want = want.sort_values(key).reset_index(drop=True)
    assert list(got.columns) == list(want.columns)
    for c in got.columns:
        if got[c].dtype.kind in ("f",):
            np.testing.assert_allclose(got[c], want[c], rtol=1e-5)
        else:
            np.testing.assert_array_equal(np.asarray(got[c]), np.asarray(want[c]))


def test_dp8_groupby_parity(dist8, lineitem_ds):
    _check_against_local(dist8, _q1(), lineitem_ds)


def test_dp4_tp2_groups_sharded_parity(dist4x2, lineitem_ds):
    _check_against_local(dist4x2, _q1(), lineitem_ds)


def test_dp8_sketches_parity(dist8, lineitem_ds):
    q = GroupByQuery(
        datasource="tpch",
        dimensions=(DimensionSpec("l_returnflag"),),
        aggregations=(
            HyperUnique("hll", "l_orderkey"),
            ThetaSketch("theta", "l_orderkey", size=1024),
            Count("n"),
        ),
    )
    _check_against_local(dist8, q, lineitem_ds)


def test_dp8_topn(dist8, ssb_ds):
    q = TopNQuery(
        datasource="ssb",
        dimension=DimensionSpec("c_city"),
        metric="rev",
        threshold=5,
        aggregations=(DoubleSum("rev", "lo_revenue"),),
        filter=Bound("d_year", lower="1993", upper="1995", ordering="numeric"),
    )
    got = DistributedEngine(mesh=make_mesh(n_data=8)).execute(q, ssb_ds)
    want = Engine().execute(q, ssb_ds)
    assert list(got.c_city) == list(want.c_city)
    np.testing.assert_allclose(got.rev, want.rev, rtol=1e-5)


def test_distributed_transient_retry(lineitem_ds):
    """A transient RuntimeError in the SPMD path evicts shards/programs and
    re-dispatches once (mirror of the local engine's retry)."""
    dist = DistributedEngine(mesh=make_mesh(n_data=8))
    q = _q1()
    # make the SPMD program fail exactly once via the builder
    calls = {"n": 0}
    orig = DistributedEngine._spmd_fn

    def flaky(self, lowering, local_rows, ds, col_keys):
        fn = orig(self, lowering, local_rows, ds, col_keys)
        if calls["n"] == 0:
            def poisoned(cols):
                calls["n"] += 1
                raise RuntimeError("injected transient SPMD failure")

            return poisoned
        return fn

    dist._spmd_fn = flaky.__get__(dist)
    got = dist.execute(q, lineitem_ds)
    want = Engine().execute(q, lineitem_ds)
    assert calls["n"] == 1  # poisoned program ran exactly once
    key = [d.name for d in q.dimensions]
    got = got.sort_values(key).reset_index(drop=True)
    want = want.sort_values(key).reset_index(drop=True)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["sum_qty"], want["sum_qty"], rtol=1e-5)
