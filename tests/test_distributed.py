"""Distributed (8-device CPU mesh) execution parity vs the local engine.

The multi-chip contract (SURVEY.md §4 implication #3): sharded execution with
ICI-collective merge must produce the same results as single-device — exact
for counts/min/max/sketch states, tight rtol for float sums (different
reduction grouping)."""

import jax
import numpy as np
import pytest

from spark_druid_olap_tpu.exec.engine import Engine
from spark_druid_olap_tpu.models.aggregations import (
    Count,
    DoubleMax,
    DoubleMin,
    DoubleSum,
    ExpressionAgg,
    HyperUnique,
    ThetaSketch,
)
from spark_druid_olap_tpu.models.dimensions import DimensionSpec
from spark_druid_olap_tpu.models.filters import Bound, Selector
from spark_druid_olap_tpu.models.query import GroupByQuery, TopNQuery
from spark_druid_olap_tpu.parallel.distributed import DistributedEngine
from spark_druid_olap_tpu.parallel.mesh import make_mesh
from spark_druid_olap_tpu.plan.expr import col


@pytest.fixture(scope="module")
def dist8():
    assert len(jax.devices()) >= 8, "conftest must provide 8 CPU devices"
    return DistributedEngine(mesh=make_mesh(n_data=8))


@pytest.fixture(scope="module")
def dist4x2():
    return DistributedEngine(mesh=make_mesh(n_data=4, n_groups=2))


def _q1():
    return GroupByQuery(
        datasource="tpch",
        dimensions=(
            DimensionSpec("l_returnflag"),
            DimensionSpec("l_linestatus"),
        ),
        aggregations=(
            DoubleSum("sum_qty", "l_quantity"),
            ExpressionAgg(
                "sum_disc_price",
                col("l_extendedprice") * (1 - col("l_discount")),
            ),
            DoubleMin("min_p", "l_extendedprice"),
            DoubleMax("max_p", "l_extendedprice"),
            Count("n"),
        ),
        filter=Selector("l_linestatus", "F"),
    )


def _check_against_local(dist, q, ds):
    got = dist.execute(q, ds)
    want = Engine().execute(q, ds)
    key = [d.name for d in q.dimensions] if isinstance(q, GroupByQuery) else None
    if key:
        got = got.sort_values(key).reset_index(drop=True)
        want = want.sort_values(key).reset_index(drop=True)
    assert list(got.columns) == list(want.columns)
    for c in got.columns:
        if got[c].dtype.kind in ("f",):
            np.testing.assert_allclose(got[c], want[c], rtol=1e-5)
        else:
            np.testing.assert_array_equal(np.asarray(got[c]), np.asarray(want[c]))


def test_dp8_groupby_parity(dist8, lineitem_ds):
    _check_against_local(dist8, _q1(), lineitem_ds)


def test_dp4_tp2_groups_sharded_parity(dist4x2, lineitem_ds):
    _check_against_local(dist4x2, _q1(), lineitem_ds)


def test_dp8_sketches_parity(dist8, lineitem_ds):
    q = GroupByQuery(
        datasource="tpch",
        dimensions=(DimensionSpec("l_returnflag"),),
        aggregations=(
            HyperUnique("hll", "l_orderkey"),
            ThetaSketch("theta", "l_orderkey", size=1024),
            Count("n"),
        ),
    )
    _check_against_local(dist8, q, lineitem_ds)


def test_dp8_topn(dist8, ssb_ds):
    q = TopNQuery(
        datasource="ssb",
        dimension=DimensionSpec("c_city"),
        metric="rev",
        threshold=5,
        aggregations=(DoubleSum("rev", "lo_revenue"),),
        filter=Bound("d_year", lower="1993", upper="1995", ordering="numeric"),
    )
    got = DistributedEngine(mesh=make_mesh(n_data=8)).execute(q, ssb_ds)
    want = Engine().execute(q, ssb_ds)
    assert list(got.c_city) == list(want.c_city)
    np.testing.assert_allclose(got.rev, want.rev, rtol=1e-5)


def test_distributed_transient_retry(lineitem_ds):
    """A transient RuntimeError in the SPMD path evicts shards/programs and
    re-dispatches once (mirror of the local engine's retry)."""
    dist = DistributedEngine(mesh=make_mesh(n_data=8))
    # pin the legacy per-shard path: this test poisons its builder
    # (`_spmd_fn`); the arena path's retry is covered separately below
    dist.arena_execution = False
    q = _q1()
    # make the SPMD program fail exactly once via the builder
    calls = {"n": 0}
    orig = DistributedEngine._spmd_fn

    def flaky(self, lowering, local_rows, ds, col_keys, strategy="dense",
              key_extra=()):
        fn = orig(self, lowering, local_rows, ds, col_keys, strategy,
                  key_extra=key_extra)
        if calls["n"] == 0:
            def poisoned(cols):
                calls["n"] += 1
                raise RuntimeError("injected transient SPMD failure")

            return poisoned
        return fn

    dist._spmd_fn = flaky.__get__(dist)
    got = dist.execute(q, lineitem_ds)
    want = Engine().execute(q, lineitem_ds)
    assert calls["n"] == 1  # poisoned program ran exactly once
    key = [d.name for d in q.dimensions]
    got = got.sort_values(key).reset_index(drop=True)
    want = want.sort_values(key).reset_index(drop=True)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["sum_qty"], want["sum_qty"], rtol=1e-5)


# -- kernel ladder on the mesh (VERDICT r4 #1) ------------------------------
#
# The distributed engine routes the same four-rung ladder as the local one:
# dense/Pallas one-hot, segment scatter, sparse sort-compaction (slots
# ladder included), and adaptive dictionary-domain compaction.  These pin
# every tier at G >= 500K on the 8-device CPU mesh, with group-domain
# sharding (groups axis) covered too.


def _high_g_ds(n=120_000, da=900, db=900, populated=2_000, seed=3, segs=4,
               name="hcm"):
    """Combined domain da*db = 810K (> 500K), few distinct pairs present —
    the SSB q3_x/q4_x shape that was modelled-only on the round-4 mesh."""
    from spark_druid_olap_tpu.catalog.segment import (
        DimensionDict,
        build_datasource,
    )

    rng = np.random.default_rng(seed)
    pairs = rng.choice(da * db, size=populated, replace=False)
    pick = rng.integers(0, populated, size=n)
    cols = {
        "a": (pairs[pick] // db).astype(np.int64),
        "b": (pairs[pick] % db).astype(np.int64),
        "v": (rng.random(n) * 100).astype(np.float32),
    }
    ds = build_datasource(
        name,
        cols,
        dimension_cols=["a", "b"],
        metric_cols=["v"],
        rows_per_segment=n // segs,
        dicts={
            "a": DimensionDict(values=tuple(range(da))),
            "b": DimensionDict(values=tuple(range(db))),
        },
    )
    return ds, cols


def _high_g_query(name="hcm", filter=None):
    from spark_druid_olap_tpu.models.aggregations import DoubleMax, DoubleMin

    return GroupByQuery(
        datasource=name,
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(
            Count("n"),
            DoubleSum("s", "v"),
            DoubleMin("lo", "v"),
            DoubleMax("hi", "v"),
        ),
        filter=filter,
    )


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2)])
def test_mesh_sparse_high_cardinality_parity(mesh_shape):
    """Sparse sort-compaction SPMD at G=810K: per-device compaction,
    all_gather+merge fold over the data axis, group-domain sharding over
    the groups axis.  Parity vs the local engine."""
    ds, _ = _high_g_ds()
    q = _high_g_query()
    dist = DistributedEngine(
        mesh=make_mesh(n_data=mesh_shape[0], n_groups=mesh_shape[1]),
        strategy="sparse",
    )
    got = dist.execute(q, ds)
    assert dist.last_metrics.strategy == "sparse"
    want = Engine(strategy="sparse").execute(q, ds)
    key = ["a", "b"]
    got = got.sort_values(key).reset_index(drop=True)
    want = want.sort_values(key).reset_index(drop=True)
    assert len(got) == len(want) == 2_000
    np.testing.assert_array_equal(got["n"], want["n"])
    for c in ("s", "lo", "hi"):
        np.testing.assert_allclose(got[c], want[c], rtol=2e-5)


def test_mesh_sparse_slots_ladder_rungs_up():
    """More distinct present than SPARSE_SLOTS: the mesh engine reruns on
    the segmented-reduce rung (slots ladder) instead of failing, and the
    rung is remembered for repeats."""
    ds, cols = _high_g_ds(n=90_000, populated=6_000, name="hcm2")
    q = _high_g_query(name="hcm2")
    dist = DistributedEngine(mesh=make_mesh(n_data=8), strategy="sparse")
    got = dist.execute(q, ds)
    # the DS-level 6000 distinct overflowed the 4096-slot one-hot tier: a
    # segmented-reduce rung was remembered so repeats skip the base tier
    from spark_druid_olap_tpu.exec.lowering import (
        groupby_with_time_granularity,
        memo_key,
    )

    # learned rungs key segment-set-independently (the ingest tier's
    # memo contract, shared with the local engine)
    qkey = memo_key(groupby_with_time_granularity(q), ds)
    assert dist._sparse_slots.get(qkey, 0) > 4096
    import pandas as pd

    df = pd.DataFrame({k: np.asarray(v) for k, v in cols.items()})
    want = (
        df.groupby(["a", "b"], as_index=False)
        .agg(n=("v", "count"), s=("v", "sum"))
        .sort_values(["a", "b"])
        .reset_index(drop=True)
    )
    got = got.sort_values(["a", "b"]).reset_index(drop=True)
    assert len(got) == len(want) == 6_000
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    # repeat goes straight through (remembered rung or base tier), parity
    got2 = dist.execute(q, ds)
    got2 = got2.sort_values(["a", "b"]).reset_index(drop=True)
    np.testing.assert_array_equal(got2["n"], want["n"])


def test_mesh_adaptive_compaction_parity():
    """Adaptive domain compaction as a distributed phase A/B: presence
    counts psum-merge over the data axis, kept-code LUTs broadcast, phase B
    runs the compact domain.  A selective filter keeps few codes."""
    ds, cols = _high_g_ds(name="hcm3")
    keep = list(range(0, 30))
    from spark_druid_olap_tpu.models.filters import InFilter

    q = _high_g_query(name="hcm3", filter=InFilter("a", tuple(keep)))
    dist = DistributedEngine(mesh=make_mesh(n_data=8), strategy="adaptive")
    got = dist.execute(q, ds)
    assert dist.last_metrics.strategy == "adaptive"
    # compacted domain engaged: far fewer groups than the full 810K
    assert dist.last_metrics.num_groups < 100_000
    mask = np.isin(cols["a"], keep)
    import pandas as pd

    df = pd.DataFrame({k: np.asarray(v) for k, v in cols.items()})[mask]
    want = (
        df.groupby(["a", "b"], as_index=False)
        .agg(n=("v", "count"), s=("v", "sum"))
        .sort_values(["a", "b"])
        .reset_index(drop=True)
    )
    got = got.sort_values(["a", "b"]).reset_index(drop=True)
    assert len(got) == len(want)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    # kept sets cached: repeat skips phase A (still correct)
    got2 = dist.execute(q, ds).sort_values(["a", "b"]).reset_index(drop=True)
    np.testing.assert_array_equal(got2["n"], want["n"])


def test_mesh_auto_routes_high_g_and_matches_local():
    """'auto' on the mesh routes by the same calibrated cost model as the
    local engine — a G=810K query EXECUTES (round 4: modelled-only) and
    matches the local result, whatever class the platform picks."""
    ds, _ = _high_g_ds(name="hcm4")
    q = _high_g_query(name="hcm4")
    dist = DistributedEngine(mesh=make_mesh(n_data=8))
    got = dist.execute(q, ds)
    assert dist.last_metrics.strategy in (
        "segment", "sparse", "adaptive", "dense", "pallas"
    )
    want = Engine().execute(q, ds)
    key = ["a", "b"]
    got = got.sort_values(key).reset_index(drop=True)
    want = want.sort_values(key).reset_index(drop=True)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)


def test_mesh_shard_residency_durable_across_queries():
    """VERDICT r4 #3: shard assembly is keyed on (datasource, column), not
    the query's pruned scope — a second, differently-filtered query over
    the same columns reuses the placed shards (h2d_ms ~ 0)."""
    ds, _ = _high_g_ds(name="hcm5")
    dist = DistributedEngine(mesh=make_mesh(n_data=8), strategy="segment")
    q1 = _high_g_query(name="hcm5")
    dist.execute(q1, ds)
    first_h2d = dist.last_metrics.h2d_bytes
    assert first_h2d > 0  # first touch pays assembly
    from spark_druid_olap_tpu.models.filters import Selector

    q2 = _high_g_query(name="hcm5", filter=Selector("a", 3))
    dist.execute(q2, ds)
    assert dist.last_metrics.h2d_bytes == 0  # durable residency: no re-place
    assert dist.last_metrics.h2d_ms == 0.0


def test_mesh_adaptive_interval_scoped_query():
    """Review r5 regression: phase A must fetch the PHYSICAL time column —
    an interval-scoped query used to KeyError out of the presence pass and
    silently decline adaptive (both engines)."""
    from spark_druid_olap_tpu.catalog.segment import (
        DimensionDict,
        build_datasource,
    )

    rng = np.random.default_rng(5)
    n, da, db = 60_000, 900, 900
    cols = {
        "a": rng.integers(0, 40, n),  # few present codes: compaction wins
        "b": rng.integers(0, 40, n),
        "t": np.sort(rng.integers(0, 1000, n)),
        "v": np.ones(n, np.float32),
    }
    ds = build_datasource(
        "hcm6", cols, dimension_cols=["a", "b"], metric_cols=["v"],
        time_col="t", rows_per_segment=30_000,
        dicts={
            "a": DimensionDict(values=tuple(range(da))),
            "b": DimensionDict(values=tuple(range(db))),
        },
    )
    q = GroupByQuery(
        datasource="hcm6",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(Count("n"), DoubleSum("s", "v")),
        intervals=((0, 500),),
    )
    dist = DistributedEngine(mesh=make_mesh(n_data=8), strategy="adaptive")
    got = dist.execute(q, ds)
    assert dist.last_metrics.strategy == "adaptive"  # no silent decline
    import pandas as pd

    df = pd.DataFrame({k: np.asarray(v) for k, v in cols.items()})
    df = df[df.t < 500]
    want = (
        df.groupby(["a", "b"], as_index=False)
        .agg(n=("v", "count"), s=("v", "sum"))
        .sort_values(["a", "b"]).reset_index(drop=True)
    )
    got = got.sort_values(["a", "b"]).reset_index(drop=True)
    np.testing.assert_array_equal(got["n"], want["n"])
    # the local engine too (same shared presence-column helper)
    eng = Engine(strategy="adaptive")
    lgot = eng.execute(q, ds).sort_values(["a", "b"]).reset_index(drop=True)
    assert eng.last_metrics.strategy == "adaptive"
    np.testing.assert_array_equal(lgot["n"], want["n"])


def test_mesh_adaptive_rekeys_sketches():
    """Adaptive phase B re-keys SKETCH states through the compacted domain
    (the compact program IS the normal SPMD program over a rewritten
    lowering) — HLL estimates must match the local engine's adaptive path
    exactly on both mesh shapes."""
    from spark_druid_olap_tpu.catalog.segment import (
        DimensionDict,
        build_datasource,
    )
    from spark_druid_olap_tpu.models.aggregations import HyperUnique
    from spark_druid_olap_tpu.models.filters import InFilter

    rng = np.random.default_rng(7)
    n, da, db = 100_000, 900, 900
    pairs = rng.choice(da * db, size=1500, replace=False)
    pick = pairs[rng.integers(0, 1500, n)]
    cols = {
        "a": (pick // db).astype(np.int64),
        "b": (pick % db).astype(np.int64),
        "k": rng.integers(0, 5000, n).astype(np.int64),
        "v": rng.random(n).astype(np.float32),
    }
    ds = build_datasource(
        "hcsk", cols, dimension_cols=["a", "b"], metric_cols=["v", "k"],
        rows_per_segment=25_000,
        dicts={
            "a": DimensionDict(values=tuple(range(da))),
            "b": DimensionDict(values=tuple(range(db))),
        },
    )
    q = GroupByQuery(
        datasource="hcsk",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(
            Count("n"),
            DoubleSum("s", "v"),
            HyperUnique("u", "k"),
        ),
        filter=InFilter("a", tuple(range(0, 40))),
    )
    want = Engine(strategy="adaptive").execute(q, ds)
    for shape in ((8, 1), (4, 2)):
        dist = DistributedEngine(
            mesh=make_mesh(n_data=shape[0], n_groups=shape[1]),
            strategy="adaptive",
        )
        got = dist.execute(q, ds)
        assert dist.last_metrics.strategy == "adaptive", shape
        key = ["a", "b"]
        g = got.sort_values(key).reset_index(drop=True)
        w = want.sort_values(key).reset_index(drop=True)
        np.testing.assert_array_equal(g["n"], w["n"])
        # HLL registers merge by max: estimates are deterministic integers
        np.testing.assert_array_equal(
            g["u"].astype(np.int64), w["u"].astype(np.int64)
        )
        np.testing.assert_allclose(g["s"], w["s"], rtol=2e-5)


def test_mesh_sparse_filtered_aggs_and_minmax():
    """Per-agg FILTER masks and min/max identities survive the sparse
    mesh path's compaction + cross-device merge fold."""
    from spark_druid_olap_tpu.catalog.segment import (
        DimensionDict,
        build_datasource,
    )
    from spark_druid_olap_tpu.models.aggregations import (
        DoubleMax,
        DoubleMin,
        FilteredAgg,
    )
    from spark_druid_olap_tpu.models.filters import Selector

    rng = np.random.default_rng(13)
    n, da, db = 80_000, 700, 700
    pairs = rng.choice(da * db, size=900, replace=False)
    pick = pairs[rng.integers(0, 900, n)]
    cols = {
        "a": (pick // db).astype(np.int64),
        "b": (pick % db).astype(np.int64),
        "flag": rng.integers(0, 3, n).astype(np.int64),
        "v": (rng.random(n) * 50).astype(np.float32),
    }
    ds = build_datasource(
        "hcfa", cols, dimension_cols=["a", "b", "flag"],
        metric_cols=["v"], rows_per_segment=20_000,
        dicts={
            "a": DimensionDict(values=tuple(range(da))),
            "b": DimensionDict(values=tuple(range(db))),
            "flag": DimensionDict(values=(0, 1, 2)),
        },
    )
    q = GroupByQuery(
        datasource="hcfa",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(
            Count("n"),
            FilteredAgg(Selector("flag", 1), DoubleSum("s1", "v")),
            DoubleMin("lo", "v"),
            DoubleMax("hi", "v"),
        ),
    )
    dist = DistributedEngine(mesh=make_mesh(n_data=8), strategy="sparse")
    got = dist.execute(q, ds)
    assert dist.last_metrics.strategy == "sparse"
    import pandas as pd

    df = pd.DataFrame({k: np.asarray(x) for k, x in cols.items()})
    df["v64"] = df.v.astype(np.float64)
    want = df.groupby(["a", "b"], as_index=False).agg(
        n=("v64", "count"), lo=("v64", "min"), hi=("v64", "max")
    )
    s1 = (
        df[df.flag == 1].groupby(["a", "b"])["v64"].sum()
        .reindex(list(zip(want.a, want.b)), fill_value=0.0)
        .to_numpy()
    )
    got = got.sort_values(["a", "b"]).reset_index(drop=True)
    want = want.sort_values(["a", "b"]).reset_index(drop=True)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["lo"], want["lo"], rtol=1e-6)
    np.testing.assert_allclose(got["hi"], want["hi"], rtol=1e-6)
    np.testing.assert_allclose(
        np.nan_to_num(got["s1"].to_numpy(np.float64)), s1, rtol=2e-5,
        atol=1e-9,
    )


def test_mesh_shards_only_pruned_scope(dist8):
    """r5->r6 mesh regression guard, restated for the unified executor
    core: the regression was re-placing and re-scanning the FULL segment
    set per query.  The SPMD arena inverts the old fix — placement is
    scope-INDEPENDENT (one durable stacked layout keyed on the full
    segment signature, never a query's scope) and the pruned scope rides
    as DATA (membership + window), so a second, disjoint-scope query
    must place ZERO new shards and both scoped results must still match
    the local engine exactly."""
    from spark_druid_olap_tpu.catalog.segment import build_datasource
    from spark_druid_olap_tpu.exec.engine import segments_in_scope

    n = 16_384
    rng = np.random.default_rng(5)
    cols = {
        "d": np.array(
            [f"k{i}" for i in rng.integers(0, 4, size=n)], dtype=object
        ),
        # integer-valued f32 keeps the psum merge bit-exact
        "v": rng.integers(0, 1000, size=n).astype(np.float32),
        "t": (np.arange(n) * 1_000).astype(np.int64),
    }
    ds = build_datasource(
        "mesh_scope", cols, dimension_cols=["d"], metric_cols=["v"],
        time_col="t", rows_per_segment=2_048,
    )

    def scoped(lo, hi):
        return GroupByQuery(
            datasource="mesh_scope",
            dimensions=(DimensionSpec("d"),),
            aggregations=(Count("n"), DoubleSum("s", "v")),
            intervals=((lo, hi),),
        )

    q1 = scoped(0, 4_096_000)
    q2 = scoped(8_192_000, 12_288_000)  # disjoint from q1's segments
    s1 = {s.uid for s in segments_in_scope(q1, ds)}
    s2 = {s.uid for s in segments_in_scope(q2, ds)}
    assert 0 < len(s1) < len(ds.segments)
    assert s1.isdisjoint(s2) and s2

    dist8.clear_cache()
    got1 = dist8.execute(q1, ds)
    keys1 = {k for k in dist8._shard_cache if k[0] == "mesh_scope"}
    # the arena's keys carry the FULL segment signature, never a scope:
    # one "spmd_arena"-tagged stack per column (+ validity)
    assert keys1 and all(k[1] == "spmd_arena" for k in keys1)
    all_uids = tuple(s.uid for s in ds.segments)
    assert all(k[3] == all_uids for k in keys1)
    got2 = dist8.execute(q2, ds)
    keys2 = {k for k in dist8._shard_cache if k[0] == "mesh_scope"}
    # disjoint scope, zero new placements: scope is data, not placement
    assert keys2 == keys1
    for q, got in ((q1, got1), (q2, got2)):
        want = Engine().execute(q, ds)
        got = got.sort_values(["d"]).reset_index(drop=True)
        want = want.sort_values(["d"]).reset_index(drop=True)
        np.testing.assert_array_equal(
            np.asarray(got["n"]), np.asarray(want["n"])
        )
        np.testing.assert_array_equal(
            np.asarray(got["s"]), np.asarray(want["s"])
        )


# -- unified executor core (ISSUE 15) ---------------------------------------
#
# The mesh is a PLACEMENT STRATEGY over the segment-stacked arena: both
# backends lower the one fold program, so every serving feature must
# produce byte-identical answers on the virtual mesh.  Integer-valued
# float32 metrics keep the psum boundary merge bit-exact (sums of
# integers inside the f32 exact range), making assert_array_equal the
# right oracle — not allclose.


def _unified_ds(name="unified", n=32_768, rows_per_segment=2_048):
    from spark_druid_olap_tpu.catalog.segment import build_datasource

    rng = np.random.default_rng(0)
    cols = {
        "d": rng.integers(0, 7, n),
        "e": rng.integers(0, 5, n),
        "v": rng.integers(0, 100, n).astype(np.float32),
        "t": (np.arange(n) * 100).astype(np.int64),
    }
    return build_datasource(
        name, cols, dimension_cols=["d", "e"], metric_cols=["v"],
        time_col="t", rows_per_segment=rows_per_segment,
    )


def _unified_queries(name="unified"):
    q1 = GroupByQuery(
        datasource=name, dimensions=(DimensionSpec("d"),),
        aggregations=(
            Count("n"), DoubleSum("s", "v"),
            DoubleMin("lo", "v"), DoubleMax("hi", "v"),
        ),
    )
    q2 = GroupByQuery(
        datasource=name, dimensions=(DimensionSpec("e"),),
        aggregations=(Count("n"), DoubleSum("s", "v")),
    )
    q3 = TopNQuery(
        datasource=name, dimension=DimensionSpec("d"), metric="s",
        threshold=3, aggregations=(DoubleSum("s", "v"),),
    )
    return q1, q2, q3


def _frames_identical(got, want, key=None):
    if key:
        got = got.sort_values(key).reset_index(drop=True)
        want = want.sort_values(key).reset_index(drop=True)
    assert list(got.columns) == list(want.columns)
    for c in got.columns:
        np.testing.assert_array_equal(np.asarray(got[c]), np.asarray(want[c]))


def test_distributed_transient_retry_arena():
    """The arena path's mirror of the transient-retry contract: a
    poisoned SPMD-arena program fails once, the shared retry policy
    evicts this query's programs + shards, and the re-dispatch answers
    exactly."""
    ds = _unified_ds("retry_arena")
    q1, _, _ = _unified_queries("retry_arena")
    dist = DistributedEngine(mesh=make_mesh(n_data=8))
    calls = {"n": 0}
    orig = DistributedEngine._arena_spmd_fn

    def flaky(self, lowering, dsrc, layout, Lk, strategy, tree):
        fn = orig(self, lowering, dsrc, layout, Lk, strategy, tree)
        if calls["n"] == 0:
            def poisoned(cols, j_lo, memb):
                calls["n"] += 1
                raise RuntimeError("injected transient SPMD failure")

            return poisoned
        return fn

    dist._arena_spmd_fn = flaky.__get__(dist)
    got = dist.execute(q1, ds)
    assert calls["n"] == 1  # poisoned program ran exactly once
    _frames_identical(got, Engine().execute(q1, ds), key=["d"])


@pytest.fixture(scope="module")
def unified_ds():
    return _unified_ds()


@pytest.fixture(
    scope="module", params=["flat8", "slice2x4"],
    ids=["mesh8", "slice2x4"],
)
def unified_dist(request):
    from spark_druid_olap_tpu.parallel.mesh import make_slice_mesh

    if request.param == "flat8":
        return DistributedEngine(mesh=make_mesh(n_data=8))
    return DistributedEngine(mesh=make_slice_mesh(2, 4))


def test_unified_matrix_exact_and_fused(unified_dist, unified_ds):
    """Feature-parity matrix rows 1-2: plain execution and micro-batch
    fusion are byte-identical to the single-device engine, on both the
    flat mesh and the 2-slice topology (whose merge tree the cost model
    picks)."""
    eng = Engine()
    q1, q2, q3 = _unified_queries()
    _frames_identical(
        unified_dist.execute(q1, unified_ds), eng.execute(q1, unified_ds),
        key=["d"],
    )
    assert all(unified_dist.fusable(q, unified_ds) for q in (q1, q2, q3))
    got = unified_dist.execute_fused(
        [q1, q2, q3], unified_ds, query_ids=["a", "b", "c"]
    )
    want = eng.execute_fused(
        [q1, q2, q3], unified_ds, query_ids=["a", "b", "c"]
    )
    for (gdf, gst, gm), (wdf, wst, wm) in zip(got, want):
        assert gm.distributed and gm.fused_batch == 3
        for k in ("sums", "mins", "maxs"):
            np.testing.assert_array_equal(gst[k], wst[k])
        _frames_identical(
            gdf.reset_index(drop=True), wdf.reset_index(drop=True)
        )


def test_unified_matrix_result_cache_states(unified_dist, unified_ds):
    """Matrix row 3: the result cache's currency — captured state, delta
    partials, ⊕-merge, finalize — is byte-identical across backends, and
    delta scans of one query share ONE compiled program (scope is data,
    not a program key)."""
    eng = Engine()
    q1, _, _ = _unified_queries()
    with unified_dist.state_capture() as cap_d:
        unified_dist.execute(q1, unified_ds)
    with eng.state_capture() as cap_e:
        eng.execute(q1, unified_ds)
    assert cap_d["state"] is not None
    for k in ("sums", "mins", "maxs"):
        np.testing.assert_array_equal(cap_d["state"][k], cap_e["state"][k])

    uids = [s.uid for s in unified_ds.segments]
    sa, ra = unified_dist.groupby_partials_host(
        q1, unified_ds, within_uids=uids[:5]
    )
    wa, wr = eng.groupby_partials_host(q1, unified_ds, within_uids=uids[:5])
    assert ra == wr
    for k in ("sums", "mins", "maxs"):
        np.testing.assert_array_equal(sa[k], wa[k])
    sb, _ = unified_dist.groupby_partials_host(
        q1, unified_ds, within_uids=uids[5:]
    )
    merged = unified_dist.merge_groupby_states(q1, unified_ds, sa, sb)
    full = unified_dist.finalize_groupby_state(q1, unified_ds, merged)
    _frames_identical(full, eng.execute(q1, unified_ds), key=["d"])
    # delta reuse: an equal-width window of the SAME query compiles no
    # new program — membership/window ride as data
    before = len(unified_dist._spmd_cache)
    unified_dist.groupby_partials_host(q1, unified_ds, within_uids=uids[2:7])
    assert len(unified_dist._spmd_cache) == before


def test_unified_matrix_deadline_partials(unified_dist, unified_ds):
    """Matrix row 4: deadline partials.  A roomy deadline answers in
    full (coverage 1.0) byte-identically; an expired-at-entry deadline
    degrades to the SAME best-effort empty answer the local engine
    gives (partial, coverage 0.0, zero rows) instead of raising."""
    from spark_druid_olap_tpu.resilience import deadline_scope, partial_scope

    eng = Engine()
    q1, _, _ = _unified_queries()
    with partial_scope(True) as pc, deadline_scope(60_000):
        got = unified_dist.execute(q1, unified_ds)
    assert not pc.is_partial and pc.coverage() == 1.0
    _frames_identical(got, eng.execute(q1, unified_ds), key=["d"])

    with partial_scope(True) as pc_d, deadline_scope(0.001):
        got_d = unified_dist.execute(q1, unified_ds)
    with partial_scope(True) as pc_e, deadline_scope(0.001):
        got_e = eng.execute(q1, unified_ds)
    assert pc_d.is_partial and pc_e.is_partial
    assert pc_d.coverage() == pc_e.coverage() == 0.0
    assert len(got_d) == len(got_e) == 0


def test_unified_matrix_prefetch_residency(unified_ds):
    """Matrix row 5: the PR 10 prefetch plan feeds the arena placement —
    a prefetched query pays ZERO foreground h2d bytes, and residency is
    durable across queries (no re-placement on re-execution)."""
    q1, _, _ = _unified_queries()
    dist = DistributedEngine(mesh=make_mesh(n_data=8))
    assert dist.prefetch(q1, unified_ds)
    dist.execute(q1, unified_ds)
    assert dist.last_metrics.h2d_bytes == 0
    dist.execute(q1, unified_ds)
    assert dist.last_metrics.h2d_bytes == 0
