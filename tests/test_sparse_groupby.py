"""Sort-compaction (sparse) GroupBy: parity vs scatter, overflow fallback.

High-cardinality domains route through ops/sparse_groupby.py (unique-compact
then dense-kernel); these are the differential tests pinning it to the
scatter path and a float64 numpy oracle."""

import numpy as np
import pandas as pd
import pytest

from spark_druid_olap_tpu.catalog.segment import build_datasource
from spark_druid_olap_tpu.exec.engine import Engine
from spark_druid_olap_tpu.models.aggregations import (
    Count,
    DoubleMax,
    DoubleMin,
    DoubleSum,
)
from spark_druid_olap_tpu.models.dimensions import DimensionSpec
from spark_druid_olap_tpu.models.filters import InFilter
from spark_druid_olap_tpu.models.query import GroupByQuery


def _make_ds(n=60_000, da=300, db=300, populated=700, seed=3, segs=3):
    """Combined domain da*db >> 4096, but only `populated` distinct pairs
    actually present (the SSB q3_x shape)."""
    rng = np.random.default_rng(seed)
    pairs = rng.choice(da * db, size=populated, replace=False)
    pick = rng.integers(0, populated, size=n)
    a = (pairs[pick] // db).astype(np.int64)
    b = (pairs[pick] % db).astype(np.int64)
    cols = {
        "a": a,
        "b": b,
        "v": (rng.random(n) * 100).astype(np.float32),
    }
    dicts = {
        "a": None,
        "b": None,
    }
    from spark_druid_olap_tpu.catalog.segment import DimensionDict

    dicts = {
        "a": DimensionDict(values=tuple(range(da))),
        "b": DimensionDict(values=tuple(range(db))),
    }
    return (
        build_datasource(
            "hc",
            cols,
            dimension_cols=["a", "b"],
            metric_cols=["v"],
            rows_per_segment=n // segs,
            dicts=dicts,
        ),
        cols,
    )


def _query(filter=None):
    return GroupByQuery(
        datasource="hc",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(
            Count("n"),
            DoubleSum("s", "v"),
            DoubleMin("lo", "v"),
            DoubleMax("hi", "v"),
        ),
        filter=filter,
    )


def _oracle(cols, mask=None):
    df = pd.DataFrame(
        {k: np.asarray(v, dtype=np.float64) for k, v in cols.items()}
    )
    if mask is not None:
        df = df[mask]
    g = df.groupby(["a", "b"], as_index=False).agg(
        n=("v", "count"), s=("v", "sum"), lo=("v", "min"), hi=("v", "max")
    )
    return g.sort_values(["a", "b"]).reset_index(drop=True)


def _norm(df):
    out = df.sort_values(["a", "b"]).reset_index(drop=True)
    return out.assign(
        a=out.a.astype(np.float64),
        b=out.b.astype(np.float64),
        n=out.n.astype(np.int64),
    )


def test_sparse_parity_vs_oracle_and_scatter():
    ds, cols = _make_ds()
    q = _query()
    sparse_eng = Engine()  # auto -> sparse at this G
    got = _norm(sparse_eng.execute(q, ds))
    want = _oracle(cols)
    np.testing.assert_array_equal(got["a"], want["a"])
    np.testing.assert_array_equal(got["b"], want["b"])
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    np.testing.assert_allclose(got["lo"], want["lo"], rtol=1e-6)
    np.testing.assert_allclose(got["hi"], want["hi"], rtol=1e-6)

    # parity with the scatter path (f32 adds reassociate under the sort
    # permutation, so near-equality not bit-equality)
    scatter_eng = Engine(strategy="scatter")
    want2 = _norm(scatter_eng.execute(q, ds))
    np.testing.assert_array_equal(got[["a", "b", "n"]], want2[["a", "b", "n"]])
    for c in ("s", "lo", "hi"):
        np.testing.assert_allclose(got[c], want2[c], rtol=1e-6)


def test_sparse_with_filter():
    ds, cols = _make_ds()
    keep = list(range(0, 50))
    q = _query(filter=InFilter("a", tuple(keep)))
    got = _norm(Engine().execute(q, ds))
    mask = np.isin(cols["a"], keep)
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)


def test_sparse_overflow_falls_back_to_scatter():
    """More distinct groups than SPARSE_SLOTS: overflow flag must trip and
    the engine must still return exact results (scatter rerun)."""
    from spark_druid_olap_tpu.ops.sparse_groupby import SPARSE_SLOTS

    n = 40_000
    da = db = 300
    rng = np.random.default_rng(11)
    # ~ min(n, 90000) distinct pairs >> SPARSE_SLOTS
    a = rng.integers(0, da, size=n)
    b = rng.integers(0, db, size=n)
    cols = {"a": a, "b": b, "v": np.ones(n, np.float32)}
    from spark_druid_olap_tpu.catalog.segment import DimensionDict

    ds = build_datasource(
        "hc2",
        cols,
        dimension_cols=["a", "b"],
        metric_cols=["v"],
        dicts={
            "a": DimensionDict(values=tuple(range(da))),
            "b": DimensionDict(values=tuple(range(db))),
        },
    )
    df = pd.DataFrame(cols)
    distinct = len(df.groupby(["a", "b"]))
    assert distinct > SPARSE_SLOTS

    # explicit 'sparse': auto only self-upgrades on TPU backends now
    eng = Engine(strategy="sparse")
    q = _query()
    q = GroupByQuery(
        datasource="hc2",
        dimensions=q.dimensions,
        aggregations=(Count("n"), DoubleSum("s", "v")),
    )
    got = eng.execute(q, ds)
    assert len(got) == distinct
    assert int(got["n"].sum()) == n
    assert eng._sparse_disabled  # the fallback actually triggered
    # second run takes the pinned scatter path directly
    got2 = eng.execute(q, ds)
    pd.testing.assert_frame_equal(
        got.sort_values(["a", "b"]).reset_index(drop=True),
        got2.sort_values(["a", "b"]).reset_index(drop=True),
    )


def test_sparse_multi_segment_merge():
    ds, cols = _make_ds(segs=5)
    assert len(ds.segments) >= 5
    q = _query()
    got = _norm(Engine().execute(q, ds))
    want = _oracle(cols)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    np.testing.assert_allclose(got["lo"], want["lo"], rtol=1e-6)
    np.testing.assert_allclose(got["hi"], want["hi"], rtol=1e-6)


def test_explicit_sparse_strategy_low_cardinality_falls_back():
    """Engine(strategy='sparse') on a low-G query must resolve to a normal
    kernel, not crash in partial_aggregate."""
    ds, cols = _make_ds(da=4, db=4, populated=10)
    got = _norm(Engine(strategy="sparse").execute(_query(), ds))
    want = _oracle(cols)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)


def test_exactly_slots_groups_with_masked_rows_no_overflow():
    """SPARSE_SLOTS real groups + filtered-out rows must fit (the trash run
    has its own reserved slot)."""
    from spark_druid_olap_tpu.catalog.segment import DimensionDict
    from spark_druid_olap_tpu.ops.sparse_groupby import SPARSE_SLOTS

    k = SPARSE_SLOTS
    n = 4 * k
    a = np.arange(n) % k           # k distinct values
    b = (np.arange(n) // k) % 2    # half the rows filtered out (masked)
    v = np.ones(n, np.float32)
    ds = build_datasource(
        "hc3",
        {"a": a, "b": b, "v": v},
        dimension_cols=["a", "b"],
        metric_cols=["v"],
        dicts={
            "a": DimensionDict(values=tuple(range(k))),
            "b": DimensionDict(values=tuple(range(2 * SPARSE_SLOTS))),
        },
    )
    eng = Engine()
    q = GroupByQuery(
        datasource="hc3",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(Count("n"), DoubleSum("s", "v")),
        filter=InFilter("b", (0,)),  # masks the b=1 half -> trash run exists
    )
    got = eng.execute(q, ds)
    assert len(got) == k
    assert not eng._sparse_disabled  # no spurious overflow at capacity
    assert int(got["n"].sum()) == n // 2


def test_sparse_empty_result():
    ds, _ = _make_ds()
    q = _query(filter=InFilter("a", (99999,)))
    got = Engine().execute(q, ds)
    assert len(got) == 0


# ---------------------------------------------------------------------------
# Filter-compaction fast path (compact_rows tier)
# ---------------------------------------------------------------------------


def test_compact_rows_parity():
    """Compacted sparse aggregation == full sparse aggregation when the
    survivors fit the row capacity."""
    import jax.numpy as jnp

    from spark_druid_olap_tpu.ops.sparse_groupby import (
        sparse_partial_aggregate,
    )

    rng = np.random.default_rng(21)
    R, G = 32_768, 1 << 20
    gid = jnp.asarray(rng.integers(0, G, size=R).astype(np.int32))
    mask = jnp.asarray(rng.random(R) < 0.02)  # ~650 survivors
    sv = jnp.asarray(rng.random((R, 2)).astype(np.float32))
    mmv = jnp.asarray(rng.random((R, 1)).astype(np.float32))
    mmm = jnp.ones((R, 1), jnp.bool_)
    full = sparse_partial_aggregate(
        gid, mask, sv, mmv, mmm, num_groups=G, num_min=1, num_max=0
    )
    comp = sparse_partial_aggregate(
        gid, mask, sv, mmv, mmm, num_groups=G, num_min=1, num_max=0,
        row_capacity=2048,
    )
    assert not bool(comp["row_overflow"])
    assert not bool(comp["overflow"])
    # same populated slots, same partials (order within the sort is by gid,
    # identical in both)
    fsel = np.asarray(full["gids"]) >= 0
    csel = np.asarray(comp["gids"]) >= 0
    np.testing.assert_array_equal(
        np.sort(np.asarray(full["gids"])[fsel]),
        np.sort(np.asarray(comp["gids"])[csel]),
    )
    fo = np.argsort(np.asarray(full["gids"])[fsel])
    co = np.argsort(np.asarray(comp["gids"])[csel])
    np.testing.assert_allclose(
        np.asarray(full["sums"])[fsel][fo],
        np.asarray(comp["sums"])[csel][co],
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(full["mins"])[fsel][fo],
        np.asarray(comp["mins"])[csel][co],
        rtol=1e-6,
    )


def test_compact_rows_overflow_flag():
    import jax.numpy as jnp

    from spark_druid_olap_tpu.ops.sparse_groupby import (
        sparse_partial_aggregate,
    )

    R = 8_192
    gid = jnp.zeros(R, jnp.int32)
    mask = jnp.ones(R, jnp.bool_)  # every row survives > capacity
    sv = jnp.ones((R, 1), jnp.float32)
    mmv = jnp.zeros((R, 0), jnp.float32)
    mmm = jnp.zeros((R, 0), jnp.bool_)
    out = sparse_partial_aggregate(
        gid, mask, sv, mmv, mmm, num_groups=1 << 16, num_min=0, num_max=0,
        row_capacity=1024,
    )
    assert bool(out["row_overflow"])


def test_engine_row_overflow_reruns_full_sort(monkeypatch):
    """Survivors exceed the compaction capacity: the engine must rerun the
    full-segment sort tier and still return exact results."""
    import spark_druid_olap_tpu.ops.sparse_groupby as sg

    monkeypatch.setattr(sg, "ROW_CAPACITY", 1024)
    ds, cols = _make_ds()  # 60k rows over 3 segments
    keep = list(range(0, 150))  # ~half the rows survive >> 1024
    q = _query(filter=InFilter("a", tuple(keep)))
    eng = Engine()
    got = _norm(eng.execute(q, ds))
    mask = np.isin(cols["a"], keep)
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)


def test_engine_ladder_picks_intermediate_rung(monkeypatch):
    """Survivors overflow the base capacity but fit a ladder rung: the
    engine must pick that rung (not the full sort), remember it, and stay
    exact."""
    import spark_druid_olap_tpu.ops.sparse_groupby as sg

    monkeypatch.setattr(sg, "ROW_CAPACITY", 1024)
    monkeypatch.setattr(sg, "ROW_CAPACITY_LADDER", (1024, 4096, 16384))
    ds, cols = _make_ds()  # 60k rows over 3 segments (20k rows each)
    keep = list(range(0, 30))  # ~6k survivors: >1024, fits 4096-per-segment
    q = _query(filter=InFilter("a", tuple(keep)))
    mask = np.isin(cols["a"], keep)
    assert 1024 < int(mask.sum()) // 3 < 4096
    eng = Engine(strategy="sparse")
    got = _norm(eng.execute(q, ds))
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    # the rung was remembered for this (query, data)
    (cap,) = eng._sparse_row_capacity.values()
    assert cap == 4096
    # repeat goes straight to the remembered rung and stays exact
    got2 = _norm(eng.execute(q, ds))
    np.testing.assert_array_equal(got2["n"], want["n"])


def test_engine_ladder_exhausted_falls_back_to_full_sort(monkeypatch):
    """Survivors past the top rung: full-segment sort, still exact."""
    import spark_druid_olap_tpu.ops.sparse_groupby as sg

    monkeypatch.setattr(sg, "ROW_CAPACITY", 1024)
    monkeypatch.setattr(sg, "ROW_CAPACITY_LADDER", (1024, 2048))
    ds, cols = _make_ds()
    keep = list(range(0, 150))  # ~half the rows survive >> 2048 per segment
    q = _query(filter=InFilter("a", tuple(keep)))
    eng = Engine(strategy="sparse")
    got = _norm(eng.execute(q, ds))
    mask = np.isin(cols["a"], keep)
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    (cap,) = eng._sparse_row_capacity.values()
    assert cap is None


def test_engine_compacted_tier_parity(monkeypatch):
    """Survivors fit the (shrunken) capacity: the compacted tier answers and
    matches the oracle."""
    import spark_druid_olap_tpu.ops.sparse_groupby as sg

    monkeypatch.setattr(sg, "ROW_CAPACITY", 8192)
    ds, cols = _make_ds()
    keep = list(range(0, 20))  # ~4k survivors < 8192
    q = _query(filter=InFilter("a", tuple(keep)))
    eng = Engine()
    got = _norm(eng.execute(q, ds))
    mask = np.isin(cols["a"], keep)
    assert int(mask.sum()) < 8192
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    np.testing.assert_allclose(got["lo"], want["lo"], rtol=1e-6)
    np.testing.assert_allclose(got["hi"], want["hi"], rtol=1e-6)
