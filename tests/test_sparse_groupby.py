"""Sort-compaction (sparse) GroupBy: parity vs scatter, overflow fallback.

High-cardinality domains route through ops/sparse_groupby.py (unique-compact
then dense-kernel); these are the differential tests pinning it to the
scatter path and a float64 numpy oracle."""

import numpy as np
import pandas as pd
import pytest

from spark_druid_olap_tpu.catalog.segment import build_datasource
from spark_druid_olap_tpu.exec.engine import Engine
from spark_druid_olap_tpu.models.aggregations import (
    Count,
    DoubleMax,
    DoubleMin,
    DoubleSum,
)
from spark_druid_olap_tpu.models.dimensions import DimensionSpec
from spark_druid_olap_tpu.models.filters import InFilter
from spark_druid_olap_tpu.models.query import GroupByQuery


def _make_ds(n=60_000, da=300, db=300, populated=700, seed=3, segs=3):
    """Combined domain da*db >> 4096, but only `populated` distinct pairs
    actually present (the SSB q3_x shape)."""
    rng = np.random.default_rng(seed)
    pairs = rng.choice(da * db, size=populated, replace=False)
    pick = rng.integers(0, populated, size=n)
    a = (pairs[pick] // db).astype(np.int64)
    b = (pairs[pick] % db).astype(np.int64)
    cols = {
        "a": a,
        "b": b,
        "v": (rng.random(n) * 100).astype(np.float32),
    }
    dicts = {
        "a": None,
        "b": None,
    }
    from spark_druid_olap_tpu.catalog.segment import DimensionDict

    dicts = {
        "a": DimensionDict(values=tuple(range(da))),
        "b": DimensionDict(values=tuple(range(db))),
    }
    return (
        build_datasource(
            "hc",
            cols,
            dimension_cols=["a", "b"],
            metric_cols=["v"],
            rows_per_segment=n // segs,
            dicts=dicts,
        ),
        cols,
    )


def _query(filter=None):
    return GroupByQuery(
        datasource="hc",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(
            Count("n"),
            DoubleSum("s", "v"),
            DoubleMin("lo", "v"),
            DoubleMax("hi", "v"),
        ),
        filter=filter,
    )


def _oracle(cols, mask=None):
    df = pd.DataFrame(
        {k: np.asarray(v, dtype=np.float64) for k, v in cols.items()}
    )
    if mask is not None:
        df = df[mask]
    g = df.groupby(["a", "b"], as_index=False).agg(
        n=("v", "count"), s=("v", "sum"), lo=("v", "min"), hi=("v", "max")
    )
    return g.sort_values(["a", "b"]).reset_index(drop=True)


def _norm(df):
    out = df.sort_values(["a", "b"]).reset_index(drop=True)
    return out.assign(
        a=out.a.astype(np.float64),
        b=out.b.astype(np.float64),
        n=out.n.astype(np.int64),
    )


def test_sparse_parity_vs_oracle_and_scatter():
    ds, cols = _make_ds()
    q = _query()
    sparse_eng = Engine()  # auto -> sparse at this G
    got = _norm(sparse_eng.execute(q, ds))
    want = _oracle(cols)
    np.testing.assert_array_equal(got["a"], want["a"])
    np.testing.assert_array_equal(got["b"], want["b"])
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    np.testing.assert_allclose(got["lo"], want["lo"], rtol=1e-6)
    np.testing.assert_allclose(got["hi"], want["hi"], rtol=1e-6)

    # parity with the scatter path (f32 adds reassociate under the sort
    # permutation, so near-equality not bit-equality)
    scatter_eng = Engine(strategy="scatter")
    want2 = _norm(scatter_eng.execute(q, ds))
    np.testing.assert_array_equal(got[["a", "b", "n"]], want2[["a", "b", "n"]])
    for c in ("s", "lo", "hi"):
        np.testing.assert_allclose(got[c], want2[c], rtol=1e-6)


def test_sparse_with_filter():
    ds, cols = _make_ds()
    keep = list(range(0, 50))
    q = _query(filter=InFilter("a", tuple(keep)))
    got = _norm(Engine().execute(q, ds))
    mask = np.isin(cols["a"], keep)
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)


def _overflow_ds(n=40_000, da=300, db=300, seed=11, name="hc2"):
    rng = np.random.default_rng(seed)
    a = rng.integers(0, da, size=n)
    b = rng.integers(0, db, size=n)
    cols = {"a": a, "b": b, "v": np.ones(n, np.float32)}
    from spark_druid_olap_tpu.catalog.segment import DimensionDict

    ds = build_datasource(
        name,
        cols,
        dimension_cols=["a", "b"],
        metric_cols=["v"],
        dicts={
            "a": DimensionDict(values=tuple(range(da))),
            "b": DimensionDict(values=tuple(range(db))),
        },
    )
    return ds, cols


def test_sparse_overflow_rungs_up_slots_ladder():
    """More distinct groups than SPARSE_SLOTS: the engine now rungs up the
    SLOTS_LADDER (segmented-reduce tier, VERDICT r3 #2) instead of
    abandoning the device path — results exact, rung remembered."""
    from spark_druid_olap_tpu.exec.lowering import memo_key
    from spark_druid_olap_tpu.ops.sparse_groupby import SPARSE_SLOTS

    ds, cols = _overflow_ds()
    df = pd.DataFrame(cols)
    distinct = len(df.groupby(["a", "b"]))
    assert distinct > SPARSE_SLOTS

    # explicit 'sparse': auto only self-upgrades on TPU backends now
    eng = Engine(strategy="sparse")
    q = GroupByQuery(
        datasource="hc2",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(Count("n"), DoubleSum("s", "v")),
    )
    got = eng.execute(q, ds)
    assert len(got) == distinct
    assert int(got["n"].sum()) == n_total(cols)
    # the ladder engaged (rung remembered), the query was NOT pinned off
    # learned rungs key segment-set-independently (ingest-tier
    # contract: a delta append must not forget them)
    assert memo_key(q, ds) in eng._sparse_slots
    assert not eng._sparse_disabled
    # second run goes straight to the remembered rung, same result
    got2 = eng.execute(q, ds)
    pd.testing.assert_frame_equal(
        got.sort_values(["a", "b"]).reset_index(drop=True),
        got2.sort_values(["a", "b"]).reset_index(drop=True),
    )


def n_total(cols):
    return len(cols["v"])


def test_sparse_overflow_past_ladder_top_pins_to_scatter(monkeypatch):
    """Distinct-present beyond the top SLOTS_LADDER rung: fall back to raw
    scatter and pin, exactly the old overflow behavior."""
    from spark_druid_olap_tpu.ops import sparse_groupby as _sg

    monkeypatch.setattr(_sg, "SLOTS_LADDER", (_sg.SPARSE_SLOTS, 8192))
    ds, cols = _overflow_ds(name="hc3")
    distinct = len(pd.DataFrame(cols).groupby(["a", "b"]))
    assert distinct > 8192

    eng = Engine(strategy="sparse")
    q = GroupByQuery(
        datasource="hc3",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(Count("n"), DoubleSum("s", "v")),
    )
    got = eng.execute(q, ds)
    assert len(got) == distinct
    assert int(got["n"].sum()) == len(cols["v"])
    assert eng._sparse_disabled  # pinned off the sparse path
    got2 = eng.execute(q, ds)
    pd.testing.assert_frame_equal(
        got.sort_values(["a", "b"]).reset_index(drop=True),
        got2.sort_values(["a", "b"]).reset_index(drop=True),
    )


def test_segmented_reduce_sorted_kernel_parity():
    """Direct kernel test: per-run sums/mins/maxs over sorted runs match a
    float64 numpy oracle, including run-straddles-block boundaries, masked
    rows, and a non-multiple-of-block row count."""
    import jax.numpy as jnp

    from spark_druid_olap_tpu.ops.sparse_groupby import (
        segmented_reduce_sorted,
    )

    rng = np.random.default_rng(5)
    R, n_runs = 5000, 37  # R % 1024 != 0 exercises the padding path
    # sorted run ids with runs of wildly uneven length (some longer than a
    # block, some single-row)
    cuts = np.sort(rng.choice(np.arange(1, R), size=n_runs - 1,
                              replace=False))
    slot = np.zeros(R, np.int32)
    slot[cuts] = 1
    slot = np.cumsum(slot).astype(np.int32)
    mask = rng.random(R) < 0.8
    v = (rng.random((R, 2)) * 10).astype(np.float32)
    sv = v * mask[:, None]
    mmv = (rng.random((R, 2)) * 10 - 5).astype(np.float32)
    mmm = np.ones((R, 2), np.bool_)

    sums, mins, maxs = segmented_reduce_sorted(
        jnp.asarray(slot), jnp.asarray(mask), jnp.asarray(sv),
        jnp.asarray(mmv), jnp.asarray(mmm),
        capacity=64, block_rows=1024, num_min=1, num_max=1,
    )
    sums, mins, maxs = map(np.asarray, (sums, mins, maxs))
    for r in range(n_runs):
        sel = (slot == r) & mask
        np.testing.assert_allclose(
            sums[r], sv[sel].astype(np.float64).sum(axis=0), rtol=2e-5,
            atol=1e-4,
        )
        want_min = mmv[sel, 0].min() if sel.any() else np.inf
        want_max = mmv[sel, 1].max() if sel.any() else -np.inf
        assert mins[r, 0] == np.float32(want_min)
        assert maxs[r, 0] == np.float32(want_max)
    # untouched capacity slots hold the identities
    assert (sums[n_runs:] == 0).all()
    assert (mins[n_runs:] == np.inf).all()
    assert (maxs[n_runs:] == -np.inf).all()


def test_sparse_big_slots_segmented_reduce_path():
    """sparse_partial_aggregate at slots > SPARSE_SLOTS with a non-scatter
    inner must use the segmented-reduce tier and stay exact."""
    import jax.numpy as jnp

    from spark_druid_olap_tpu.ops.sparse_groupby import (
        SPARSE_SLOTS,
        sparse_partial_aggregate,
    )

    rng = np.random.default_rng(9)
    R, G = 1 << 15, 1 << 20
    distinct = 9000
    assert distinct > SPARSE_SLOTS
    pool = rng.choice(G, size=distinct, replace=False).astype(np.int32)
    gid = pool[rng.integers(0, distinct, size=R)]
    mask = rng.random(R) < 0.9
    v = rng.random((R, 1)).astype(np.float32)
    sv = v * mask[:, None]
    st = sparse_partial_aggregate(
        jnp.asarray(gid), jnp.asarray(mask), jnp.asarray(sv),
        jnp.zeros((R, 0), jnp.float32), jnp.zeros((R, 0), jnp.bool_),
        num_groups=G, num_min=0, num_max=0,
        slots=16384, inner_strategy="dense",
    )
    assert not bool(st["overflow"])
    got_g = np.asarray(st["gids"])
    got_s = np.asarray(st["sums"])[:, 0]
    df = pd.DataFrame({"g": gid[mask], "v": v[mask, 0].astype(np.float64)})
    want = df.groupby("g")["v"].sum()
    live = got_g >= 0
    assert live.sum() == len(want)
    got = pd.Series(got_s[live], index=got_g[live]).sort_index()
    np.testing.assert_allclose(got.values, want.values, rtol=2e-5)
    np.testing.assert_array_equal(got.index.values, want.index.values)
    assert int(np.asarray(st["n_real"])) == len(want)


def test_sparse_multi_segment_merge():
    ds, cols = _make_ds(segs=5)
    assert len(ds.segments) >= 5
    q = _query()
    got = _norm(Engine().execute(q, ds))
    want = _oracle(cols)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    np.testing.assert_allclose(got["lo"], want["lo"], rtol=1e-6)
    np.testing.assert_allclose(got["hi"], want["hi"], rtol=1e-6)


def test_explicit_sparse_strategy_low_cardinality_falls_back():
    """Engine(strategy='sparse') on a low-G query must resolve to a normal
    kernel, not crash in partial_aggregate."""
    ds, cols = _make_ds(da=4, db=4, populated=10)
    got = _norm(Engine(strategy="sparse").execute(_query(), ds))
    want = _oracle(cols)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)


def test_exactly_slots_groups_with_masked_rows_no_overflow():
    """SPARSE_SLOTS real groups + filtered-out rows must fit (the trash run
    has its own reserved slot)."""
    from spark_druid_olap_tpu.catalog.segment import DimensionDict
    from spark_druid_olap_tpu.ops.sparse_groupby import SPARSE_SLOTS

    k = SPARSE_SLOTS
    n = 4 * k
    a = np.arange(n) % k           # k distinct values
    b = (np.arange(n) // k) % 2    # half the rows filtered out (masked)
    v = np.ones(n, np.float32)
    ds = build_datasource(
        "hc3",
        {"a": a, "b": b, "v": v},
        dimension_cols=["a", "b"],
        metric_cols=["v"],
        dicts={
            "a": DimensionDict(values=tuple(range(k))),
            "b": DimensionDict(values=tuple(range(2 * SPARSE_SLOTS))),
        },
    )
    eng = Engine()
    q = GroupByQuery(
        datasource="hc3",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(Count("n"), DoubleSum("s", "v")),
        filter=InFilter("b", (0,)),  # masks the b=1 half -> trash run exists
    )
    got = eng.execute(q, ds)
    assert len(got) == k
    assert not eng._sparse_disabled  # no spurious overflow at capacity
    assert int(got["n"].sum()) == n // 2


def test_sparse_empty_result():
    ds, _ = _make_ds()
    q = _query(filter=InFilter("a", (99999,)))
    got = Engine().execute(q, ds)
    assert len(got) == 0


# ---------------------------------------------------------------------------
# Filter-compaction fast path (compact_rows tier)
# ---------------------------------------------------------------------------


def test_compact_rows_parity():
    """Compacted sparse aggregation == full sparse aggregation when the
    survivors fit the row capacity."""
    import jax.numpy as jnp

    from spark_druid_olap_tpu.ops.sparse_groupby import (
        sparse_partial_aggregate,
    )

    rng = np.random.default_rng(21)
    R, G = 32_768, 1 << 20
    gid = jnp.asarray(rng.integers(0, G, size=R).astype(np.int32))
    mask = jnp.asarray(rng.random(R) < 0.02)  # ~650 survivors
    sv = jnp.asarray(rng.random((R, 2)).astype(np.float32))
    mmv = jnp.asarray(rng.random((R, 1)).astype(np.float32))
    mmm = jnp.ones((R, 1), jnp.bool_)
    full = sparse_partial_aggregate(
        gid, mask, sv, mmv, mmm, num_groups=G, num_min=1, num_max=0
    )
    comp = sparse_partial_aggregate(
        gid, mask, sv, mmv, mmm, num_groups=G, num_min=1, num_max=0,
        row_capacity=2048,
    )
    assert not bool(comp["row_overflow"])
    assert not bool(comp["overflow"])
    # same populated slots, same partials (order within the sort is by gid,
    # identical in both)
    fsel = np.asarray(full["gids"]) >= 0
    csel = np.asarray(comp["gids"]) >= 0
    np.testing.assert_array_equal(
        np.sort(np.asarray(full["gids"])[fsel]),
        np.sort(np.asarray(comp["gids"])[csel]),
    )
    fo = np.argsort(np.asarray(full["gids"])[fsel])
    co = np.argsort(np.asarray(comp["gids"])[csel])
    np.testing.assert_allclose(
        np.asarray(full["sums"])[fsel][fo],
        np.asarray(comp["sums"])[csel][co],
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(full["mins"])[fsel][fo],
        np.asarray(comp["mins"])[csel][co],
        rtol=1e-6,
    )


def test_compact_rows_overflow_flag():
    import jax.numpy as jnp

    from spark_druid_olap_tpu.ops.sparse_groupby import (
        sparse_partial_aggregate,
    )

    R = 8_192
    gid = jnp.zeros(R, jnp.int32)
    mask = jnp.ones(R, jnp.bool_)  # every row survives > capacity
    sv = jnp.ones((R, 1), jnp.float32)
    mmv = jnp.zeros((R, 0), jnp.float32)
    mmm = jnp.zeros((R, 0), jnp.bool_)
    out = sparse_partial_aggregate(
        gid, mask, sv, mmv, mmm, num_groups=1 << 16, num_min=0, num_max=0,
        row_capacity=1024,
    )
    assert bool(out["row_overflow"])


def test_engine_row_overflow_reruns_full_sort(monkeypatch):
    """Survivors exceed the compaction capacity: the engine must rerun the
    full-segment sort tier and still return exact results."""
    import spark_druid_olap_tpu.ops.sparse_groupby as sg

    monkeypatch.setattr(sg, "ROW_CAPACITY", 1024)
    ds, cols = _make_ds()  # 60k rows over 3 segments
    keep = list(range(0, 150))  # ~half the rows survive >> 1024
    q = _query(filter=InFilter("a", tuple(keep)))
    eng = Engine()
    got = _norm(eng.execute(q, ds))
    mask = np.isin(cols["a"], keep)
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)


def test_engine_ladder_picks_intermediate_rung(monkeypatch):
    """Survivors overflow the base capacity but fit a ladder rung: the
    engine must pick that rung (not the full sort), remember it, and stay
    exact."""
    import spark_druid_olap_tpu.ops.sparse_groupby as sg

    monkeypatch.setattr(sg, "ROW_CAPACITY", 1024)
    monkeypatch.setattr(sg, "ROW_CAPACITY_LADDER", (1024, 4096, 16384))
    # force a bad (tiny) selectivity estimate so the initial rung is the
    # ladder bottom and the OVERFLOW path is what gets exercised
    from spark_druid_olap_tpu.plan import cost as plan_cost
    monkeypatch.setattr(
        plan_cost, "estimate_selectivity", lambda f, ds: 1e-4
    )
    ds, cols = _make_ds()  # 60k rows over 3 segments (20k rows each)
    keep = list(range(0, 30))  # ~6k survivors: >1024, fits 4096-per-segment
    q = _query(filter=InFilter("a", tuple(keep)))
    mask = np.isin(cols["a"], keep)
    assert 1024 < int(mask.sum()) // 3 < 4096
    eng = Engine(strategy="sparse")
    got = _norm(eng.execute(q, ds))
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    # the rung was remembered for this (query, data)
    (cap,) = eng._sparse_row_capacity.values()
    assert cap == 4096
    # repeat goes straight to the remembered rung and stays exact
    got2 = _norm(eng.execute(q, ds))
    np.testing.assert_array_equal(got2["n"], want["n"])


def test_engine_ladder_exhausted_falls_back_to_full_sort(monkeypatch):
    """Survivors past the top rung: full-segment sort, still exact."""
    import spark_druid_olap_tpu.ops.sparse_groupby as sg

    monkeypatch.setattr(sg, "ROW_CAPACITY", 1024)
    monkeypatch.setattr(sg, "ROW_CAPACITY_LADDER", (1024, 2048))
    from spark_druid_olap_tpu.plan import cost as plan_cost
    monkeypatch.setattr(
        plan_cost, "estimate_selectivity", lambda f, ds: 1e-4
    )
    ds, cols = _make_ds()
    keep = list(range(0, 150))  # ~half the rows survive >> 2048 per segment
    q = _query(filter=InFilter("a", tuple(keep)))
    eng = Engine(strategy="sparse")
    got = _norm(eng.execute(q, ds))
    mask = np.isin(cols["a"], keep)
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    (cap,) = eng._sparse_row_capacity.values()
    assert cap is None


def test_engine_compacted_tier_parity(monkeypatch):
    """Survivors fit the (shrunken) capacity: the compacted tier answers and
    matches the oracle."""
    import spark_druid_olap_tpu.ops.sparse_groupby as sg

    monkeypatch.setattr(sg, "ROW_CAPACITY", 8192)
    ds, cols = _make_ds()
    keep = list(range(0, 20))  # ~4k survivors < 8192
    q = _query(filter=InFilter("a", tuple(keep)))
    eng = Engine()
    got = _norm(eng.execute(q, ds))
    mask = np.isin(cols["a"], keep)
    assert int(mask.sum()) < 8192
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    np.testing.assert_allclose(got["lo"], want["lo"], rtol=1e-6)
    np.testing.assert_allclose(got["hi"], want["hi"], rtol=1e-6)


def test_selectivity_estimate_picks_initial_rung(monkeypatch):
    """A well-estimated filter goes straight to an adequate rung: no
    overflow, no remembered rung, exact results."""
    import spark_druid_olap_tpu.ops.sparse_groupby as sg

    monkeypatch.setattr(
        sg, "ROW_CAPACITY_LADDER", (1024, 4096, 16384, 65536)
    )
    ds, cols = _make_ds()  # 60k rows over 3 segments
    keep = list(range(0, 30))  # sel ~0.1 -> need ~4096/segment
    q = _query(filter=InFilter("a", tuple(keep)))
    eng = Engine(strategy="sparse")
    got = _norm(eng.execute(q, ds))
    mask = np.isin(cols["a"], keep)
    want = _oracle(cols, mask)
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_allclose(got["s"], want["s"], rtol=2e-5)
    # estimate was adequate: the overflow rung-up never had to fire
    assert eng._sparse_row_capacity == {}
