"""Unit tests for the query-lifecycle resilience primitives
(spark_druid_olap_tpu/resilience.py): error taxonomy, deadlines, circuit
breaker, admission control, fault injector."""

import threading
import time

import pytest

from spark_druid_olap_tpu import resilience as R


@pytest.fixture(autouse=True)
def _clean_injector():
    R.injector().disarm()
    yield
    R.injector().disarm()


# -- error taxonomy ---------------------------------------------------------


def test_classify_error():
    assert R.classify_error(RuntimeError("device blip")) == "transient"
    assert R.classify_error(OSError("tunnel down")) == "transient"
    assert R.classify_error(R.InjectedFault("x")) == "transient"
    assert R.classify_error(R.CircuitOpenError("x")) == "transient"
    assert R.classify_error(NotImplementedError("no such op")) == "static"
    assert R.classify_error(ValueError("bad plan")) == "static"
    assert R.classify_error(KeyError("col")) == "static"
    assert R.classify_error(TypeError("x")) == "static"
    assert R.classify_error(R.DeadlineExceeded("site", 5)) == "deadline"


# -- deadlines --------------------------------------------------------------


def test_deadline_scope_and_checkpoint():
    assert R.current_deadline() is None
    R.checkpoint("nowhere")  # no active deadline: free no-op
    with R.deadline_scope(10_000) as d:
        assert d is not None and R.current_deadline() is d
        R.checkpoint("inside")  # plenty of budget
        assert d.remaining_ms() > 5_000
    assert R.current_deadline() is None


def test_deadline_expiry_raises_with_site():
    with R.deadline_scope(1):
        time.sleep(0.005)
        with pytest.raises(R.DeadlineExceeded) as ei:
            R.checkpoint("engine.segment_loop")
        assert ei.value.site == "engine.segment_loop"
    # zero/None timeouts arm nothing
    with R.deadline_scope(0):
        assert R.current_deadline() is None
    with R.deadline_scope(None):
        assert R.current_deadline() is None


def test_outer_deadline_wins():
    """A server-set wire deadline must not be replaced by the session
    default armed inside ctx.sql."""
    with R.deadline_scope(50) as outer:
        with R.deadline_scope(600_000) as inner:
            assert inner is outer
            assert R.current_deadline() is outer
            assert R.current_deadline().timeout_ms == 50


# -- circuit breaker --------------------------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_breaker_trips_after_threshold():
    br = R.CircuitBreaker(failure_threshold=3, cooldown_ms=1000)
    assert br.state == "closed" and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"  # below threshold
    br.record_failure()
    assert br.state == "open"
    assert not br.allow()
    d = br.to_dict()
    assert d["trips"] == 1 and d["consecutive_failures"] == 3


def test_breaker_success_resets_consecutive_count():
    br = R.CircuitBreaker(failure_threshold=2)
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == "closed"  # the success broke the streak


def test_breaker_half_open_probe_and_recovery():
    clk = _FakeClock()
    br = R.CircuitBreaker(failure_threshold=1, cooldown_ms=500, clock=clk)
    br.record_failure()
    assert br.state == "open" and not br.allow()
    clk.t += 0.6  # past the cooldown
    assert br.state == "half_open"
    assert br.allow()  # the probe is admitted
    br.record_success()
    assert br.state == "closed"


def test_breaker_half_open_admits_single_probe():
    """Cooldown expiry under queued traffic must release ONE probe, not a
    thundering herd onto the possibly-still-broken device."""
    clk = _FakeClock()
    br = R.CircuitBreaker(failure_threshold=1, cooldown_ms=500, clock=clk)
    br.record_failure()
    clk.t += 0.6
    assert br.allow()  # first caller holds the probe lease
    assert not br.allow()  # everyone else keeps degrading
    assert not br.allow()
    br.record_failure()  # probe reports: re-open, lease released
    assert br.state == "open"
    clk.t += 0.6
    assert br.allow()  # fresh lease after the new cooldown
    br.record_success()
    assert br.state == "closed"
    # a probe that dies without reporting cannot wedge the breaker: the
    # lease goes stale after another cooldown interval
    br.record_failure()
    clk.t += 0.6
    assert br.allow()
    clk.t += 0.6  # lease is now stale
    assert br.allow()


def test_breaker_failed_probe_reopens():
    clk = _FakeClock()
    br = R.CircuitBreaker(failure_threshold=1, cooldown_ms=500, clock=clk)
    br.record_failure()
    clk.t += 0.6
    assert br.allow()
    br.record_failure()  # probe failed
    assert br.state == "open" and not br.allow()
    assert br.to_dict()["trips"] == 2
    clk.t += 0.6  # a fresh cooldown runs from the failed probe
    assert br.allow()
    br.record_success()
    assert br.state == "closed"


def test_breaker_release_probe_returns_lease_without_verdict():
    clk = _FakeClock()
    br = R.CircuitBreaker(failure_threshold=1, cooldown_ms=500, clock=clk)
    br.record_failure()
    clk.t += 0.6
    assert br.allow()  # lease taken
    assert not br.allow()
    br.release_probe()  # e.g. the query was served from the result cache
    assert br.state == "half_open"  # no verdict: state unchanged
    assert br.allow()  # next caller probes immediately, no stale wait
    br.record_success()
    assert br.state == "closed"


# -- admission control ------------------------------------------------------


def test_admission_slots_and_timeout():
    adm = R.AdmissionController(max_concurrent=2, queue_timeout_ms=50)
    assert adm.acquire() and adm.acquire()
    assert adm.in_use == 2
    t0 = time.perf_counter()
    assert not adm.acquire()  # full: rejected after the queue wait
    assert time.perf_counter() - t0 >= 0.04
    assert adm.rejected_total == 1
    adm.release()
    assert adm.acquire()  # a freed slot admits again
    adm.release()
    adm.release()
    assert adm.in_use == 0
    assert adm.retry_after_s() >= 1
    d = adm.to_dict()
    assert d["slots_total"] == 2 and d["admitted_total"] == 3


def test_retry_after_from_observed_hold_time():
    """Retry-After reflects the observed slot hold EWMA, not the
    configured queue wait (ROADMAP resilience follow-up (d))."""
    clk = _FakeClock()
    adm = R.AdmissionController(
        max_concurrent=1, queue_timeout_ms=30000, clock=clk
    )
    # before any observation the configured wait stands in (clamped)
    assert adm.retry_after_s() == 30
    assert adm.acquire()
    clk.t += 2.5  # the query held its slot for 2.5s
    adm.release()
    # idle pool, observed ~2.5s hold: hint is ceil(2.5) = 3, NOT 30
    assert adm.retry_after_s() == 3
    assert adm.to_dict()["hold_ewma_ms"] == pytest.approx(2500.0)


def test_retry_after_scales_with_queue_depth():
    clk = _FakeClock()
    adm = R.AdmissionController(
        max_concurrent=1, queue_timeout_ms=60000, clock=clk
    )
    # observe a 4s hold to seed the EWMA
    assert adm.acquire()
    clk.t += 4.0
    adm.release()
    # occupy the slot and queue two real waiters behind it
    assert adm.acquire()
    started = threading.Barrier(3)

    def waiter():
        started.wait(timeout=5)
        adm.acquire()  # parks until release (60s budget)
        adm.release()

    threads = [threading.Thread(target=waiter) for _ in range(2)]
    for t in threads:
        t.start()
    started.wait(timeout=5)
    deadline = time.perf_counter() + 5
    while adm.queue_depth < 2 and time.perf_counter() < deadline:
        time.sleep(0.01)
    assert adm.queue_depth == 2
    # depth 2 on 1 slot at ~4s/hold: ceil(4 * (2/1 + 1)) = 12s; an
    # unqueued pool with the same EWMA would say 4s
    assert adm.retry_after_s() == 12
    d = adm.to_dict()
    assert d["queue_depth"] == 2
    adm.release()  # drain: each waiter acquires and releases in turn
    for t in threads:
        t.join(timeout=5)
    assert adm.queue_depth == 0
    # hint is clamped to [1, 60] even under absurd observed holds
    clk2 = _FakeClock()
    adm2 = R.AdmissionController(
        max_concurrent=1, queue_timeout_ms=1000, clock=clk2
    )
    assert adm2.acquire()
    clk2.t += 500.0
    adm2.release()
    assert adm2.retry_after_s() == 60


def test_admission_queued_caller_gets_freed_slot():
    adm = R.AdmissionController(max_concurrent=1, queue_timeout_ms=2000)
    assert adm.acquire()
    got = {}

    def waiter():
        got["ok"] = adm.acquire()

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    adm.release()
    t.join(timeout=2)
    assert got["ok"] is True
    adm.release()


# -- fault injector ---------------------------------------------------------


def test_injector_error_mode_counts_down():
    inj = R.FaultInjector()
    inj.arm("device_dispatch", "error", times=2)
    with pytest.raises(R.InjectedFault):
        inj.fire("device_dispatch")
    with pytest.raises(R.InjectedFault):
        inj.fire("device_dispatch")
    inj.fire("device_dispatch")  # self-disarmed after N fires
    assert not inj.armed("device_dispatch")
    assert inj.state()["fired"]["device_dispatch"] == 2


def test_injector_delay_and_partial_modes():
    inj = R.FaultInjector()
    inj.arm("h2d", "delay", delay_ms=30)
    t0 = time.perf_counter()
    inj.fire("h2d")  # sleeps, never raises
    assert time.perf_counter() - t0 >= 0.025
    inj.arm("fallback_decode", "partial", fraction=0.5)
    # fire() must NOT consume or trip a partial spec
    inj.fire("fallback_decode")
    assert inj.partial_fraction("fallback_decode") == 0.5
    assert inj.partial_fraction("device_dispatch") is None


def test_injector_custom_error_type_and_disarm_all():
    inj = R.FaultInjector()
    inj.arm("compile", "error", error_type=OSError)
    with pytest.raises(OSError):
        inj.fire("compile")
    inj.arm("h2d", "error")
    inj.disarm()
    inj.fire("compile")
    inj.fire("h2d")


def test_injector_env_arming():
    inj = R.FaultInjector()
    inj.arm_from_env("device_dispatch:error:2, h2d:delay:5, compile:partial:0.25")
    assert inj.armed("device_dispatch")
    assert inj.armed("h2d")
    assert inj.partial_fraction("compile") == 0.25
    with pytest.raises(R.InjectedFault):
        inj.fire("device_dispatch")


def test_global_fire_noop_when_never_armed():
    # the module-level shortcut must stay free when nothing was armed
    R.fire("device_dispatch")
    R.injector().arm("device_dispatch", "error", times=1)
    with pytest.raises(R.InjectedFault):
        R.fire("device_dispatch")
    R.fire("device_dispatch")


# -- resilience state / health ---------------------------------------------


def test_resilience_state_health_shape():
    from spark_druid_olap_tpu.config import SessionConfig

    cfg = SessionConfig()
    cfg.max_concurrent_queries = 3
    cfg.breaker_failure_threshold = 5
    st = R.ResilienceState(cfg)
    st.note_degraded()
    st.note_server_error(ValueError("boom"))
    h = st.health()
    assert h["healthy"] is True
    assert h["breaker"]["state"] == "closed"
    assert h["breaker"]["failure_threshold"] == 5
    assert h["admission"]["slots_total"] == 3
    assert h["counters"]["degraded_total"] == 1
    assert h["counters"]["server_errors_total"] == 1
    assert h["counters"]["last_error"]["errorClass"] == "ValueError"
