"""Cluster tier (ISSUE 16): broker + N historicals over a shared
snapshot store — assignment math, the partial-state wire codec, and the
scatter/gather path serving EXACT answers through real HTTP.

The process model under test: historicals are in-process
`HistoricalNode`s (own `TPUOlapContext` mmap-booted from the broker's
`storage_dir`, read-only: no fsync, no flush sweep, no compaction)
behind real `OlapServer`s on ephemeral ports; the broker is a normal
durable context with a `ClusterClient` attached.  Chaos lives in
test_cluster_chaos.py; this file pins the sunny-day contracts.
"""

import json

import numpy as np
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.cluster import (
    Assignment,
    ClusterClient,
    HistoricalNode,
    build_assignment,
    decode_state,
    encode_state,
    load_assignment,
    rebalance,
    replicas_for,
    save_assignment,
    WireDecodeError,
)
from spark_druid_olap_tpu.resilience import injector

T0 = int(np.datetime64("2023-01-01", "ms").astype(np.int64))
DAY = 86_400_000


@pytest.fixture(autouse=True)
def _disarm():
    injector().disarm()
    yield
    injector().disarm()


def _cols(n=4000, seed=3):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(
            np.array(["austin", "boston", "chicago", "denver"], dtype=object),
            n,
        ),
        "qty": rng.integers(1, 100, n).astype(np.int64),
        "rev": rng.random(n).astype(np.float32),
        "ts": T0 + rng.integers(0, 30, n) * DAY,
    }


def _mk_broker(d, n=4000, rows_per_segment=1000, **cfg_kw):
    ctx = sd.TPUOlapContext(
        sd.SessionConfig(storage_dir=str(d), **cfg_kw)
    )
    ctx.register_table(
        "ev", _cols(n), dimensions=["city"], metrics=["qty", "rev"],
        time_column="ts", rows_per_segment=rows_per_segment,
    )
    return ctx


class _Cluster:
    """Broker + N in-process historicals over one directory."""

    def __init__(self, d, n_nodes=2, replication=2, **cfg_kw):
        self.broker = _mk_broker(d, **cfg_kw)
        self.nodes = {}
        for i in range(n_nodes):
            h = HistoricalNode(f"h{i}", str(d)).start()
            self.nodes[h.node_id] = h
        self.client = ClusterClient(
            self.broker,
            nodes={nid: h.url for nid, h in self.nodes.items()},
            replication=replication,
        ).attach()

    def close(self):
        self.client.close()
        for h in self.nodes.values():
            h.shutdown()


@pytest.fixture()
def cluster(tmp_path):
    c = _Cluster(tmp_path)
    yield c
    c.close()


# -- wire codec ---------------------------------------------------------------


def _state(g=5, a=3, m=2, w=8):
    rng = np.random.default_rng(0)
    return {
        "sums": rng.random((g, a)),
        "mins": rng.random((g, m)),
        "maxs": rng.random((g, m)),
        "sketches": {"hll$u": rng.integers(0, 255, (g, w)).astype(np.uint8)},
    }


def test_wire_roundtrip_preserves_dtype_shape_values():
    st = _state()
    out = decode_state(json.loads(json.dumps(encode_state(st))))
    for k in ("sums", "mins", "maxs"):
        assert out[k].dtype == st[k].dtype
        assert np.array_equal(out[k], st[k])
    assert np.array_equal(st["sketches"]["hll$u"], out["sketches"]["hll$u"])
    # decoded arrays must be writable: the ⊕ accumulates in place
    out["sums"][0, 0] = 7.0


def test_wire_decode_rejects_torn_and_malformed():
    doc = encode_state(_state())
    with pytest.raises(WireDecodeError):
        decode_state(None)
    bad = json.loads(json.dumps(doc))
    bad["sums"]["data"] = bad["sums"]["data"][: len(bad["sums"]["data"]) // 2]
    with pytest.raises(WireDecodeError):
        decode_state(bad)
    bad2 = json.loads(json.dumps(doc))
    bad2["mins"]["shape"] = [999, 999]  # byte count vs shape mismatch
    with pytest.raises(WireDecodeError):
        decode_state(bad2)


# -- assignment ---------------------------------------------------------------


def test_hrw_deterministic_and_clamped():
    nodes = ["h0", "h1", "h2"]
    a = replicas_for("seg-1", nodes, 2)
    assert a == replicas_for("seg-1", list(reversed(nodes)), 2)
    assert len(a) == 2 and len(set(a)) == 2
    assert len(replicas_for("seg-1", ["h0"], 3)) == 1  # clamped


def test_hrw_minimal_movement_on_membership_change():
    sids = [f"s{i}" for i in range(64)]
    before = {s: replicas_for(s, ["h0", "h1", "h2"], 2) for s in sids}
    after = {s: replicas_for(s, ["h0", "h1"], 2) for s in sids}
    for s in sids:
        # survivors keep every segment they already held
        kept = [n for n in before[s] if n != "h2"]
        assert all(n in after[s] for n in kept), (s, before[s], after[s])


def test_assignment_rebalance_bumps_epoch_and_persists(tmp_path):
    a = build_assignment(
        {"ev": ["s1", "s2"]}, ["h0", "h1"], 2, versions={"ev": 4}
    )
    assert a.epoch == 1 and a.versions == {"ev": 4}
    b = rebalance(a, ["h0", "h1", "h2"],
                  segment_ids={"ev": ["s1", "s2"]})
    assert b.epoch == 2 and b.versions == {"ev": 4}
    save_assignment(str(tmp_path), b)
    back = load_assignment(str(tmp_path))
    assert back == b
    assert isinstance(back, Assignment)


def test_deficit_counts_under_and_lost():
    a = build_assignment({"ev": ["s1", "s2", "s3"]}, ["h0", "h1"], 2)
    assert a.deficit(["h0", "h1"]) == (0, 0)
    under, lost = a.deficit(["h0"])
    assert under == 3 and lost == 0  # every chain holds both nodes
    assert a.deficit([]) == (3, 3)


def test_broker_resumes_epoch_from_manifest(tmp_path):
    c = _Cluster(tmp_path)
    try:
        e1 = c.client.assignment.epoch
        c.client.rebalance()
        e2 = c.client.assignment.epoch
        assert e2 == e1 + 1
    finally:
        c.close()
    # a NEW broker over the same directory continues the epoch clock
    broker2 = sd.TPUOlapContext(sd.SessionConfig(storage_dir=str(tmp_path)))
    cl2 = ClusterClient(broker2, nodes={"h9": "http://127.0.0.1:1"})
    try:
        assert cl2.assignment.epoch > e2
    finally:
        cl2.close()


# -- scatter/gather end to end ------------------------------------------------


Q_GROUPBY = (
    "SELECT city, sum(qty) AS q, count(*) AS n, max(rev) AS r "
    "FROM ev GROUP BY city ORDER BY city"
)


def test_cluster_answers_equal_local(cluster):
    c = cluster
    c.client.detach()
    local = c.broker.sql(Q_GROUPBY)
    assert c.client.last_metrics is None  # detached: local path
    c.client.attach()
    # a LIMIT large enough to be a no-op dodges the result cache while
    # keeping the answer identical
    out = c.broker.sql(Q_GROUPBY + " LIMIT 100")
    m = c.client.last_metrics
    assert m is not None and m.executor == "cluster"
    assert m.strategy == "cluster" and m.distributed
    assert not m.partial
    assert local.equals(out)
    # multiple segments actually scattered
    assert m.segments >= 4


def test_cluster_result_matches_across_aggregates(cluster):
    c = cluster
    for i, q in enumerate(
        [
            "SELECT city, min(rev) AS lo, max(rev) AS hi FROM ev "
            "GROUP BY city ORDER BY city",
            "SELECT city, sum(rev) AS s FROM ev "
            "WHERE qty > 50 GROUP BY city ORDER BY city",
        ]
    ):
        local = c.broker.sql(q)
        out = c.broker.sql(q + f" LIMIT {100 + i}")
        assert c.client.last_metrics is not None
        assert local.equals(out), q


def test_fresh_deltas_are_residual_until_rebalance(cluster):
    c = cluster
    # appended rows live only in the broker's delta buffer — no flush,
    # no rebalance — yet the clustered answer must include them
    c.broker.append_rows("ev", _cols(n=500, seed=11))
    local = c.broker.sql(Q_GROUPBY)
    out = c.broker.sql(Q_GROUPBY + " LIMIT 101")
    assert c.client.last_metrics is not None
    assert local.equals(out)


def test_health_cluster_section_and_metadata_via_server(cluster):
    import urllib.request

    c = cluster
    from spark_druid_olap_tpu.server import OlapServer

    srv = OlapServer(c.broker, port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/status/health", timeout=30
        ) as r:
            doc = json.loads(r.read())
        cl = doc["cluster"]
        assert cl["live"] == 2 and cl["epoch"] >= 1
        assert cl["replication_deficit"] == 0
        assert set(cl["nodes"]) == {"h0", "h1"}
        for nd in cl["nodes"].values():
            assert nd["live"] and nd["breaker"]["state"] == "closed"
            assert nd["assigned_segments"] >= 1
        # metadata queries serve regardless of cluster state
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/druid/v2/datasources", timeout=30
        ) as r:
            assert "ev" in json.loads(r.read())
    finally:
        srv.shutdown()


def test_broker_receipt_attributes_scatter_gather_merge(cluster):
    c = cluster
    c.broker.tracer.force_sample_next()
    df = c.broker.sql(Q_GROUPBY + " LIMIT 102")
    assert c.client.last_metrics is not None
    rc = c.broker.tracer.last_trace_dict()["receipt"]
    assert rc.get("scatter_ms", 0) > 0
    assert "gather_ms" in rc and "cluster_merge_ms" in rc
    nodes = rc["cluster"]["nodes"]
    assert nodes and all(b["ok"] >= 1 for b in nodes.values())
    # single-process receipts keep their lean shape
    assert "cluster" not in (df.attrs.get("receipt") or {"cluster": 1}) or True
    # obs_dump renders the per-historical buckets
    from tools.obs_dump import render_receipts

    text = render_receipts([("q", rc)])
    assert "cluster: scatter=" in text
    for node in nodes:
        assert node in text


def test_cluster_rpc_metrics_published(cluster):
    from spark_druid_olap_tpu.obs.registry import get_registry

    c = cluster
    reg = get_registry()
    ctr = reg.counter(
        "sdol_cluster_scatter_total", labels=("node", "outcome")
    )
    base = sum(
        v for k, v in ctr.snapshot().items() if k.endswith(",ok")
    )
    c.broker.sql(Q_GROUPBY + " LIMIT 103")
    assert c.client.last_metrics is not None
    now = sum(
        v for k, v in ctr.snapshot().items() if k.endswith(",ok")
    )
    assert now - base >= 1
    c.client.state()  # publishes the health gauges
    assert reg.gauge("sdol_cluster_historicals_live").labels().value == 2
    assert (
        reg.gauge("sdol_cluster_replication_deficit").labels().value == 0
    )
