"""Tier-1 gate for tools/check_error_discipline.py: every broad `except`
in the serving/execution layers must re-raise, route through the
resilience classifier, record observably, or carry an explicit
`# fault-ok: <reason>` pragma — no silent swallows (ISSUE 1 satellite)."""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

import check_error_discipline as ced  # noqa: E402


def test_no_silent_broad_excepts():
    violations = ced.check_paths(_ROOT)
    assert not violations, "\n".join(
        f"{p}:{ln}: {msg}" for p, ln, msg in violations
    )


def test_target_set_covers_serving_and_execution():
    files = {os.path.relpath(f, _ROOT) for f in ced.target_files(_ROOT)}
    assert "spark_druid_olap_tpu/server.py" in files
    assert any(f.startswith("spark_druid_olap_tpu/exec/") for f in files)
    assert any(f.startswith("spark_druid_olap_tpu/parallel/") for f in files)


def test_checker_flags_a_silent_swallow(tmp_path):
    """The checker actually catches the bad shape (guards against the
    checker rotting into a rubber stamp)."""
    pkg = tmp_path / "spark_druid_olap_tpu"
    (pkg / "exec").mkdir(parents=True)
    (pkg / "parallel").mkdir()
    (pkg / "server.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    (pkg / "exec" / "ok.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        raise\n"
    )
    violations = ced.check_paths(str(tmp_path))
    assert len(violations) == 1
    assert violations[0][0].endswith("server.py")


def test_checker_accepts_pragma_and_logging(tmp_path):
    pkg = tmp_path / "spark_druid_olap_tpu"
    (pkg / "exec").mkdir(parents=True)
    (pkg / "parallel").mkdir()
    (pkg / "server.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # fault-ok: best-effort probe\n"
        "        pass\n"
        "def h():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        log.warning('failed', exc_info=True)\n"
    )
    assert ced.check_paths(str(tmp_path)) == []
    # a bare pragma with no reason does NOT count
    (pkg / "server.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # fault-ok:\n"
        "        pass\n"
    )
    assert len(ced.check_paths(str(tmp_path))) == 1


def test_cli_entrypoint_exit_codes(tmp_path):
    tool = os.path.join(_ROOT, "tools", "check_error_discipline.py")
    # the real repo passes
    out = subprocess.run(
        [sys.executable, tool, _ROOT], capture_output=True, text=True
    )
    assert out.returncode == 0, out.stdout + out.stderr
    # a violating tree fails
    pkg = tmp_path / "spark_druid_olap_tpu"
    (pkg / "exec").mkdir(parents=True)
    (pkg / "parallel").mkdir()
    (pkg / "server.py").write_text(
        "try:\n    x()\nexcept Exception:\n    y = 1\n"
    )
    out = subprocess.run(
        [sys.executable, tool, str(tmp_path)],
        capture_output=True, text=True,
    )
    assert out.returncode == 1
    assert "server.py" in out.stdout
