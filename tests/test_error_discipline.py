"""Tier-1 gate for the error-discipline graftlint pass (PR 1's standalone
tools/check_error_discipline.py, ported into the framework by ISSUE 2):
every broad `except` in the serving/execution layers must re-raise, route
through the resilience classifier, record observably, or carry an explicit
`# fault-ok: <reason>` pragma — no silent swallows."""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.graftlint import run_lint  # noqa: E402
from tools.graftlint.passes.error_discipline import (  # noqa: E402
    ErrorDisciplinePass,
)

_TARGETS = ["spark_druid_olap_tpu", "tests", "bench.py"]


def _check(root, paths=None):
    res = run_lint(
        root, paths or _TARGETS, pass_names=["error-discipline"],
        # an isolated fixture tree has no baseline; the repo's own run
        # (test_no_silent_broad_excepts) uses the real baseline path
        baseline_path=os.path.join(root, "graftlint_baseline.json"),
    )
    return res.new


def test_no_silent_broad_excepts():
    violations = _check(_ROOT)
    assert not violations, "\n".join(f.render() for f in violations)


def test_target_set_covers_serving_and_execution():
    include = ErrorDisciplinePass.default_config["include"]
    assert "spark_druid_olap_tpu/server.py" in include
    assert any(p.startswith("spark_druid_olap_tpu/exec") for p in include)
    assert any(p.startswith("spark_druid_olap_tpu/parallel") for p in include)


def test_checker_flags_a_silent_swallow(tmp_path):
    """The pass actually catches the bad shape (guards against the
    checker rotting into a rubber stamp)."""
    pkg = tmp_path / "spark_druid_olap_tpu"
    (pkg / "exec").mkdir(parents=True)
    (pkg / "parallel").mkdir()
    (pkg / "server.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    (pkg / "exec" / "ok.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        raise\n"
    )
    violations = _check(str(tmp_path), ["spark_druid_olap_tpu"])
    assert len(violations) == 1
    assert violations[0].path.endswith("server.py")


def test_checker_accepts_pragma_and_logging(tmp_path):
    pkg = tmp_path / "spark_druid_olap_tpu"
    (pkg / "exec").mkdir(parents=True)
    (pkg / "parallel").mkdir()
    (pkg / "server.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # fault-ok: best-effort probe\n"
        "        pass\n"
        "def h():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        log.warning('failed', exc_info=True)\n"
    )
    assert _check(str(tmp_path), ["spark_druid_olap_tpu"]) == []
    # a bare pragma with no reason does NOT count
    (pkg / "server.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # fault-ok:\n"
        "        pass\n"
    )
    assert len(_check(str(tmp_path), ["spark_druid_olap_tpu"])) == 1


def test_resilience_routing_and_metrics_count_as_discipline(tmp_path):
    pkg = tmp_path / "spark_druid_olap_tpu"
    (pkg / "exec").mkdir(parents=True)
    (pkg / "parallel").mkdir()
    (pkg / "server.py").write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as e:\n"
        "        kind = classify_error(e)\n"
        "def h():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        self._m.retries += 1\n"
    )
    assert _check(str(tmp_path), ["spark_druid_olap_tpu"]) == []


def test_cli_entrypoint_exit_codes(tmp_path):
    env = {**os.environ, "PYTHONPATH": _ROOT}
    # the real repo passes
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint",
         "--pass", "error-discipline", *_TARGETS],
        capture_output=True, text=True, cwd=_ROOT, env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    # a violating tree fails
    pkg = tmp_path / "spark_druid_olap_tpu"
    (pkg / "exec").mkdir(parents=True)
    (pkg / "parallel").mkdir()
    (pkg / "server.py").write_text(
        "try:\n    x()\nexcept Exception:\n    y = 1\n"
    )
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint",
         "--pass", "error-discipline", "spark_druid_olap_tpu"],
        capture_output=True, text=True, cwd=str(tmp_path), env=env,
    )
    assert out.returncode == 1
    assert "server.py" in out.stdout


def test_standalone_checker_is_gone():
    """ISSUE 2 satellite: the one-off tool was ported into the framework
    and deleted — a resurrected copy would drift from the pass."""
    assert not os.path.exists(
        os.path.join(_ROOT, "tools", "check_error_discipline.py")
    )
