"""Native-query degradation + progressive streaming + per-backend
breakers (ISSUE 7 tentpole (b)/(c)).

Parity contract: a wire-native query answered DEGRADED (device breaker
open / transient device failure) through the QuerySpec->logical fallback
interpreter must produce the same Druid-shaped response the healthy
device path produces — for groupBy, topN, and timeseries — and must
match the SQL fallback's answer for the equivalent SQL text.

Progressive contract: `context.progressive` streams NDJSON refinements
whose coverage grows monotonically to 1.0, with the final refinement
exactly equal to the buffered response.

Breaker contract: device / mesh / fallback breakers are independent
(visible in /status/health and as `sdol_breaker_state{backend=...}` in
/status/metrics), and a fallback sick enough to trip its own breaker
fails fast instead of re-grinding.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.config import SessionConfig
from spark_druid_olap_tpu.models.wire import query_from_druid
from spark_druid_olap_tpu.resilience import injector
from spark_druid_olap_tpu.server import OlapServer, druid_result_shape
from spark_druid_olap_tpu.utils.floatcmp import frames_allclose


@pytest.fixture(autouse=True)
def _clean_injector():
    injector().disarm()
    yield
    injector().disarm()


DAY = 86_400_000


def _make_ctx(**overrides):
    cfg = SessionConfig.load_calibrated()
    cfg.result_cache_entries = 0
    cfg.retry_backoff_ms = 1.0
    cfg.prefer_distributed = False
    for k, v in overrides.items():
        setattr(cfg, k, v)
    ctx = sd.TPUOlapContext(cfg)
    n = 8_000
    rng = np.random.default_rng(3)
    ctx.register_table(
        "ev",
        {
            "city": rng.choice(
                np.array(["NY", "SF", "LA", "CHI"], dtype=object), n
            ),
            "tier": rng.choice(np.array(["gold", "free"], dtype=object), n),
            "v": rng.integers(1, 100, n).astype(np.float32),
            "ts": (rng.integers(0, 14, n) * DAY).astype(np.int64),
        },
        dimensions=["city", "tier"],
        metrics=["v"],
        time_column="ts",
        rows_per_segment=1 << 10,
    )
    return ctx


_GROUPBY = {
    "queryType": "groupBy",
    "dataSource": "ev",
    "granularity": "all",
    "dimensions": ["city", "tier"],
    "aggregations": [
        {"type": "doubleSum", "name": "s", "fieldName": "v"},
        {"type": "count", "name": "n"},
        {
            "type": "filtered",
            "filter": {"type": "selector", "dimension": "tier",
                       "value": "gold"},
            "aggregator": {"type": "doubleSum", "name": "gold_s",
                           "fieldName": "v"},
        },
    ],
    "postAggregations": [
        {
            "type": "arithmetic", "name": "avg_v", "fn": "/",
            "fields": [
                {"type": "fieldAccess", "fieldName": "s"},
                {"type": "fieldAccess", "fieldName": "n"},
            ],
        }
    ],
    "filter": {
        "type": "in", "dimension": "city", "values": ["NY", "SF", "LA"],
    },
    "having": {"type": "greaterThan", "aggregation": "n", "value": 1},
    "intervals": ["1970-01-01T00:00:00Z/1970-01-10T00:00:00Z"],
    "limitSpec": {
        "type": "default",
        "limit": 50,
        "columns": [{"dimension": "s", "direction": "descending"}],
    },
}

_TOPN = {
    "queryType": "topN",
    "dataSource": "ev",
    "granularity": "all",
    "dimension": "city",
    "metric": "s",
    "threshold": 3,
    "aggregations": [
        {"type": "doubleSum", "name": "s", "fieldName": "v"}
    ],
    "intervals": ["1970-01-01T00:00:00Z/1970-01-15T00:00:00Z"],
}

_TIMESERIES = {
    "queryType": "timeseries",
    "dataSource": "ev",
    "granularity": "day",
    "aggregations": [
        {"type": "doubleSum", "name": "s", "fieldName": "v"},
        {"type": "count", "name": "n"},
    ],
    "filter": {"type": "selector", "dimension": "tier", "value": "gold"},
    "intervals": ["1970-01-01T00:00:00Z/1970-01-15T00:00:00Z"],
}


def _shape(ctx, spec):
    q = query_from_druid(spec)
    ds = ctx.catalog.get(q.datasource)
    return q, druid_result_shape(q, ctx.engine.execute(q, ds))


def _degraded_shape(ctx, spec, err=None):
    q = query_from_druid(spec)
    return q, druid_result_shape(
        q, ctx.execute_native_degraded(q, err, reason="test")
    )


def _canon(shaped):
    """Order-insensitive canonical form with float rounding."""

    def walk(x):
        if isinstance(x, float):
            return round(x, 6)
        if isinstance(x, dict):
            return {k: walk(v) for k, v in sorted(x.items())}
        if isinstance(x, list):
            return [walk(v) for v in x]
        return x

    return walk(shaped)


@pytest.mark.parametrize(
    "spec", [_GROUPBY, _TOPN, _TIMESERIES],
    ids=["groupBy", "topN", "timeseries"],
)
def test_native_fallback_parity_golden(spec):
    """Degraded wire response == healthy wire response, byte-for-byte
    after float rounding (groupBy rows sorted by the limitSpec metric
    may tie-break differently; these fixtures have no exact ties)."""
    ctx = _make_ctx()
    _, healthy = _shape(ctx, spec)
    _, degraded = _degraded_shape(ctx, spec)
    assert _canon(degraded) == _canon(healthy)
    m = ctx.last_metrics
    assert m.executor == "fallback" and m.degraded


def test_native_fallback_matches_sql_fallback():
    """The same aggregation written as SQL and degraded through the SQL
    path must agree with the native degraded answer (satellite golden:
    the two fallback surfaces cannot drift)."""
    ctx = _make_ctx()
    q, degraded = _degraded_shape(ctx, _TOPN)
    injector().arm("device_dispatch", "error")
    sql_df = ctx.sql(
        "SELECT city, sum(v) AS s FROM ev GROUP BY city "
        "ORDER BY s DESC LIMIT 3"
    )
    assert ctx.last_metrics.executor == "fallback"
    native_rows = degraded[0]["result"]
    assert [r["city"] for r in native_rows] == list(sql_df["city"])
    assert np.allclose(
        [r["s"] for r in native_rows], np.asarray(sql_df["s"])
    )


def test_native_degraded_over_http_on_open_breaker():
    ctx = _make_ctx(breaker_failure_threshold=1,
                    breaker_cooldown_ms=600_000)
    srv = OlapServer(ctx, port=0).start()
    try:
        _, healthy = _shape(ctx, _GROUPBY)
        dev = ctx.resilience.breaker_for("device")
        dev.record_failure()  # threshold 1: open
        assert dev.state == "open"
        body = json.dumps(_GROUPBY).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/druid/v2", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 200
            got = json.loads(r.read())
        assert _canon(got) == _canon(healthy)
        h = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/status/health", timeout=30
            ).read()
        )
        assert h["breakers"]["device"]["state"] == "open"
        assert h["breakers"]["fallback"]["state"] == "closed"
        assert h["breakers"]["mesh"]["state"] == "closed"
    finally:
        srv.shutdown()


@pytest.mark.parametrize("qtype", ["groupBy", "topN"])
def test_query_level_granularity_parity(qtype):
    """Query-level granularity (Druid's implicit leading time-bucket
    dimension on groupBy/topN) must survive degradation: collapsing all
    time buckets into one would be a silently-wrong 200."""
    ctx = _make_ctx()
    if qtype == "groupBy":
        spec = {
            "queryType": "groupBy", "dataSource": "ev",
            "granularity": "day", "dimensions": ["city"],
            "aggregations": [
                {"type": "count", "name": "n"},
                {"type": "doubleSum", "name": "s", "fieldName": "v"},
            ],
            "intervals": ["1970-01-01T00:00:00Z/1970-01-08T00:00:00Z"],
        }
    else:
        spec = {
            "queryType": "topN", "dataSource": "ev",
            "granularity": "day", "dimension": "city",
            "metric": "s", "threshold": 2,
            "aggregations": [
                {"type": "doubleSum", "name": "s", "fieldName": "v"}
            ],
            "intervals": ["1970-01-01T00:00:00Z/1970-01-08T00:00:00Z"],
        }
    _, healthy = _shape(ctx, spec)
    _, degraded = _degraded_shape(ctx, spec)
    assert sorted(_canon(degraded), key=str) == sorted(
        _canon(healthy), key=str
    )


def test_keepalive_get_never_echoes_stale_query_id():
    """HTTP/1.1 keep-alive: the same handler instance serves every
    request on a connection — a GET after a POST must not echo the
    POST's X-Druid-Query-Id on the health/metrics response."""
    import http.client

    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port)
        spec = dict(_TIMESERIES, context={"queryId": "sticky-q1"})
        conn.request(
            "POST", "/druid/v2", body=json.dumps(spec),
            headers={"Content-Type": "application/json"},
        )
        r = conn.getresponse()
        assert r.status == 200
        assert r.getheader("X-Druid-Query-Id") == "sticky-q1"
        r.read()
        conn.request("GET", "/status/health")  # same connection
        r2 = conn.getresponse()
        assert r2.status == 200
        assert r2.getheader("X-Druid-Query-Id") != "sticky-q1"
        r2.read()
        conn.close()
    finally:
        srv.shutdown()


def test_progressive_client_disconnect_is_not_a_server_error(monkeypatch):
    """A client dropping a progressive stream mid-flight must not count
    as a server error or wedge the connection handler — the dead socket
    is swallowed and the next query serves normally."""
    from spark_druid_olap_tpu import server as server_mod

    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        orig = server_mod._Handler._write_chunk
        calls = {"n": 0}

        def dying_socket(self, data):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise BrokenPipeError("client went away")
            return orig(self, data)

        monkeypatch.setattr(
            server_mod._Handler, "_write_chunk", dying_socket
        )
        before = ctx.resilience.server_errors_total
        spec = dict(_GROUPBY, context={"progressive": True})
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/druid/v2",
            data=json.dumps(spec).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req, timeout=60).read()
        except Exception:
            pass  # the truncated stream may or may not parse client-side
        assert calls["n"] >= 2  # the injected disconnect fired
        assert ctx.resilience.server_errors_total == before
        monkeypatch.setattr(server_mod._Handler, "_write_chunk", orig)
        # the server still answers normally afterwards
        req2 = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/druid/v2",
            data=json.dumps(_GROUPBY).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req2, timeout=60) as r:
            assert r.status == 200
    finally:
        srv.shutdown()


def test_scan_order_by_time_degrades():
    """Scan order-by __time must resolve against the PROJECTED column
    names (the projection renames the raw time column to __time); the
    degraded rows must match the healthy device scan."""
    ctx = _make_ctx()
    spec = {
        "queryType": "scan",
        "dataSource": "ev",
        "columns": ["__time", "city", "v"],
        "intervals": ["1970-01-01T00:00:00Z/1970-01-15T00:00:00Z"],
        "order": "ascending",
        "limit": 7,
    }
    _, healthy = _shape(ctx, spec)
    _, degraded = _degraded_shape(ctx, spec)
    assert _canon(degraded) == _canon(healthy)


def test_groupby_bare_time_dimension_parity():
    """A groupBy time dimension at granularity 'all' is a single
    all-time bucket — the device path emits the column, so the degraded
    path must too (shape parity), not silently drop it."""
    ctx = _make_ctx()
    spec = {
        "queryType": "groupBy",
        "dataSource": "ev",
        "granularity": "all",
        "dimensions": [
            "city",
            {"type": "default", "dimension": "__time",
             "outputName": "t"},
        ],
        "aggregations": [{"type": "count", "name": "n"}],
        "intervals": ["1970-01-01T00:00:00Z/1970-01-15T00:00:00Z"],
    }
    _, healthy = _shape(ctx, spec)
    _, degraded = _degraded_shape(ctx, spec)
    # no limitSpec: groupBy row order is unspecified — compare as sets
    assert sorted(_canon(degraded), key=str) == sorted(
        _canon(healthy), key=str
    )
    assert all("t" in r["event"] for r in degraded)


def test_native_deadline_outside_partial_loops_drains_to_200():
    """A deadline first observed at a NON-partial checkpoint (here:
    the device_dispatch fault site, outside every checkpoint_partial
    loop) must drain-rerun on the native surface exactly like
    api._execute_with_resilience does for SQL — a coverage-stamped 200,
    not a 504."""
    from spark_druid_olap_tpu.resilience import InjectedDeadline

    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        body = json.dumps(_TIMESERIES).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/druid/v2", data=body,
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=60).read()  # warm
        injector().arm(
            "device_dispatch", "error", times=1,
            error_type=InjectedDeadline,
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 200
            rctx = json.loads(r.headers["X-Druid-Response-Context"])
        assert rctx["partial"] is True
        assert rctx["coverage"] is not None
    finally:
        srv.shutdown()


def test_native_partial_publishes_counter_and_header():
    """A deadline-bounded answer on the NATIVE surface publishes exactly
    like the SQL surface (partial-result discipline, GL16xx): the wire
    header carries the coverage contract AND the fleet counter/histogram
    record it — not just the header."""
    from spark_druid_olap_tpu.obs import get_registry
    from spark_druid_olap_tpu.resilience import InjectedDeadline

    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        before = get_registry().counter(
            "sdol_partial_results_total", labels=("site",)
        ).snapshot()
        injector().arm(
            "engine.segment_loop", "error", times=1, skip=1,
            error_type=InjectedDeadline,
        )
        body = json.dumps(_GROUPBY).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/druid/v2", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 200
            rctx = json.loads(r.headers["X-Druid-Response-Context"])
        assert rctx["partial"] is True
        assert 0.0 <= rctx["coverage"] < 1.0
        after = get_registry().counter(
            "sdol_partial_results_total", labels=("site",)
        ).snapshot()
        assert sum(after.values()) == sum(before.values()) + 1
    finally:
        srv.shutdown()


def test_native_unsupported_shape_keeps_503_on_open_breaker():
    """Shapes the interpreter can't cover keep the fail-fast 503: a
    wrong degraded answer would be worse than no answer."""
    ctx = _make_ctx(breaker_failure_threshold=1,
                    breaker_cooldown_ms=600_000)
    srv = OlapServer(ctx, port=0).start()
    try:
        ctx.resilience.breaker_for("device").record_failure()
        spec = dict(_GROUPBY)
        spec["dimensions"] = [
            {
                "type": "extraction",
                "dimension": "city",
                "outputName": "c0",
                "extractionFn": {"type": "substring", "index": 0,
                                 "length": 1},
            }
        ]
        body = json.dumps(spec).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/druid/v2", data=body,
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After")
    finally:
        srv.shutdown()


def test_metadata_queries_served_through_open_breaker():
    """timeBoundary/segmentMetadata never dispatch device work: an open
    breaker must not block them (per-backend granularity in action)."""
    ctx = _make_ctx(breaker_failure_threshold=1,
                    breaker_cooldown_ms=600_000)
    srv = OlapServer(ctx, port=0).start()
    try:
        ctx.resilience.breaker_for("device").record_failure()
        body = json.dumps(
            {"queryType": "timeBoundary", "dataSource": "ev"}
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/druid/v2", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            out = json.loads(r.read())
        assert out and "minTime" in out[0]["result"]
    finally:
        srv.shutdown()


def test_fallback_breaker_trips_and_fails_fast():
    """Consecutive TRANSIENT fallback failures open the fallback
    breaker; while open, a degraded query fails fast with the original
    device error instead of re-grinding the sick interpreter."""
    ctx = _make_ctx(breaker_failure_threshold=2,
                    breaker_cooldown_ms=600_000)
    injector().arm("device_dispatch", "error")
    injector().arm("fallback_decode", "error")  # the decode fault site
    q = "SELECT city, sum(v) AS s FROM ev GROUP BY city"
    for _ in range(2):
        with pytest.raises(Exception):
            ctx.sql(q)
    fb = ctx.resilience.breaker_for("fallback")
    assert fb.state == "open"
    injector().disarm("fallback_decode")  # the fallback is healthy again
    # ... but its breaker is still open: fail fast, no decode attempt
    fired = injector().state()["fired"].get("fallback_decode", 0)
    with pytest.raises(Exception):
        ctx.sql(q)
    assert injector().state()["fired"].get("fallback_decode", 0) == fired
    # after the cooldown, a half-open probe recovers the backend
    fb.cooldown_ms = 0.0
    injector().disarm()
    injector().arm("device_dispatch", "error")
    df = ctx.sql(q)
    assert ctx.last_metrics.executor == "fallback"
    assert len(df) == 4 and fb.state == "closed"


def test_breaker_state_gauges_in_prometheus():
    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        ctx.resilience.breaker_for("mesh").record_failure()
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/status/metrics", timeout=30
        ).read().decode()
        for backend in ("device", "mesh", "fallback"):
            assert f'sdol_breaker_state{{backend="{backend}"}}' in text
        # closed == 0 for the untouched backends
        assert 'sdol_breaker_state{backend="device"} 0' in text
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# progressive streaming
# ---------------------------------------------------------------------------


def _post_progressive(port, spec, timeout=120):
    body = dict(spec)
    body["context"] = {**body.get("context", {}), "progressive": True}
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/druid/v2",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        qid = r.headers.get("X-Druid-Query-Id")
        lines = [
            json.loads(x) for x in r.read().decode().strip().splitlines()
        ]
    return qid, lines


@pytest.mark.parametrize(
    "spec", [_GROUPBY, _TOPN, _TIMESERIES],
    ids=["groupBy", "topN", "timeseries"],
)
def test_progressive_refinements_converge_to_exact(spec):
    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        _, healthy = _shape(ctx, spec)
        qid, lines = _post_progressive(srv.port, spec)
        assert qid
        assert len(lines) >= 2, "multiple refinements expected"
        covs = [l["coverage"] for l in lines]
        assert all(a <= b + 1e-9 for a, b in zip(covs, covs[1:]))
        assert lines[-1]["final"] is True
        assert lines[-1]["coverage"] == 1.0
        assert lines[-1]["partial"] is False
        assert _canon(lines[-1]["result"]) == _canon(healthy)
        # every refinement is well-formed druid shape
        for l in lines:
            assert isinstance(l["result"], list)
    finally:
        srv.shutdown()


def test_progressive_stream_flush_spans_in_trace():
    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        qid, lines = _post_progressive(srv.port, _TOPN)
        tr = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/druid/v2/trace/{qid}",
                timeout=30,
            ).read()
        )

        def count(node, name):
            n = 1 if node["name"] == name else 0
            return n + sum(
                count(c, name) for c in node.get("children", ())
            )

        assert count(tr["spans"], "stream_flush") == len(lines)
    finally:
        srv.shutdown()


def test_progressive_falls_back_to_buffered_for_non_aggregates():
    """Scan has no mergeable state to refine: context.progressive on a
    non-aggregate type emits one final chunk (never an error)."""
    ctx = _make_ctx()
    srv = OlapServer(ctx, port=0).start()
    try:
        spec = {
            "queryType": "scan",
            "dataSource": "ev",
            "columns": ["city", "v"],
            "limit": 5,
            "intervals": ["1970-01-01T00:00:00Z/1970-01-15T00:00:00Z"],
        }
        qid, lines = _post_progressive(srv.port, spec)
        # non-aggregate types answer buffered (no NDJSON refinement)
        assert len(lines) >= 1
    finally:
        srv.shutdown()
