"""Chunked/streamed SSB ingest (VERDICT r2 #2: the SF10+/SF100 path):
build_datasource_streamed + register_streamed must agree with the chunked
oracle without ever materializing the full fact."""

import numpy as np
import pandas as pd
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.workloads import ssb

SCALE = 0.01  # 60K fact rows, chunked into many pieces


@pytest.fixture(scope="module")
def streamed_ctx():
    ctx = sd.TPUOlapContext()
    tables = ssb.register_streamed(
        ctx, scale=SCALE, seed=7,
        rows_per_segment=1 << 14, chunk_rows=10_000,  # NOT a multiple: the
        # remainder buffer in build_datasource_streamed is exercised
    )
    return ctx, tables


def _merged_oracle(tables, name):
    parts = [
        ssb.oracle(ssb.flat_frame_chunk(tables, lo), name)
        for lo in ssb.fact_chunks(SCALE, 7, 10_000, tables)
    ]
    return ssb.merge_oracle_parts(parts)


def test_streamed_segments_and_counts(streamed_ctx):
    ctx, tables = streamed_ctx
    ds = ctx.catalog.get("lineorder")
    assert ds.num_rows == 60_000
    assert len(ds.segments) == -(-60_000 // (1 << 14))
    # segment ids are globally renumbered and unique
    ids = [s.segment_id for s in ds.segments]
    assert len(set(ids)) == len(ids)
    got = ctx.sql("SELECT count(*) AS n FROM lineorder")
    assert int(got["n"].iloc[0]) == 60_000


def test_streamed_scalar_query_parity(streamed_ctx):
    ctx, tables = streamed_ctx
    got = ctx.sql(ssb.QUERIES["q1_1"])
    want = _merged_oracle(tables, "q1_1")
    np.testing.assert_allclose(
        float(got["revenue"].iloc[0]), want, rtol=2e-4
    )


def test_streamed_grouped_query_parity(streamed_ctx):
    ctx, tables = streamed_ctx
    got = ctx.sql(ssb.QUERIES["q4_2"]).sort_values(
        ["d_year", "s_nation", "p_category"]
    ).reset_index(drop=True)
    want = _merged_oracle(tables, "q4_2").sort_values(
        ["d_year", "s_nation", "p_category"]
    ).reset_index(drop=True)
    assert len(got) == len(want)
    for c in ("d_year", "s_nation", "p_category"):
        assert list(got[c].astype(str)) == list(want[c].astype(str))
    np.testing.assert_allclose(
        got["profit"].astype(float), want["profit"], rtol=2e-4
    )


def test_streamed_dict_requirement():
    from spark_druid_olap_tpu.catalog.segment import (
        build_datasource_streamed,
    )

    with pytest.raises(ValueError, match="global dictionary"):
        build_datasource_streamed(
            "x",
            iter([{"c": np.array(["a", "b"], dtype=object)}]),
            dimension_cols=["c"],
            metric_cols=[],
        )


def test_gen_tables_unchanged_by_refactor():
    """gen_tables must stay byte-identical ACROSS REFACTORS (rng draw
    order): pinned by a checksum of the SF0.001 fact.  Round-5 rebaseline:
    pre-sorted int16 date generation (_gen_fact) deliberately changed the
    rng stream — bench.py's oracle cache version was bumped in the same
    commit; any future mismatch here without such a bump is a bug."""
    t = ssb.gen_tables(scale=0.001, seed=7)
    lo = t["lineorder"]
    assert len(lo["lo_custkey"]) == 6_000
    assert int(lo["lo_custkey"].sum()) == 298_323
    assert int(lo["lo_suppkey"].sum()) == 146_596
    assert int(lo["lo_partkey"].sum()) == 598_578
    assert round(
        float(np.asarray(lo["lo_revenue"], np.float64).sum()), 2
    ) == 160_034_403.61


def test_parallel_ingest_matches_serial(tmp_path):
    """workers>0 (sharded THREAD pipeline, ISSUE 8 follow-up 2(a)) must
    register a byte-identical datasource to the single-worker path — the
    sharded dictionary merge and ordered shard reassembly are pure
    functions of the row set.  Each side runs in a fresh python child so
    the hashes cover a cold end-to-end register_streamed."""
    import hashlib
    import os
    import subprocess
    import sys

    import numpy as np

    import spark_druid_olap_tpu as sd

    digest_src = r"""
import hashlib, sys
import numpy as np
import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.workloads import ssb

ctx = sd.TPUOlapContext()
ssb.register_streamed(ctx, scale=0.02, seed=7, workers=int(sys.argv[1]))
ds = ctx.catalog.get("lineorder")
h = hashlib.sha256()
h.update(str(ds.num_rows).encode())
for seg in ds.segments:
    h.update(str(seg.num_rows).encode())
    h.update(np.ascontiguousarray(np.asarray(seg.time)).tobytes())
    for n in ("c_city", "p_brand1", "lo_revenue"):
        h.update(np.ascontiguousarray(np.asarray(seg.column(n))).tobytes())
print(h.hexdigest())
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))

    def run(workers: int) -> str:
        p = subprocess.run(
            [sys.executable, "-c", digest_src, str(workers)],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert p.returncode == 0, p.stderr[-2000:]
        return p.stdout.strip().splitlines()[-1]

    assert run(0) == run(2)


def test_streamed_ingest_throughput_floor():
    """Ingest-regression canary (VERDICT r4 #5): the streamed encode path
    at a fixed size must clear a CONSERVATIVE rows/s floor.  The measured
    rate on this container is ~2.2M rows/s after the round-5 hot-loop work
    (narrow attr codes at the dictionary, int16-day radix sort, int32 FK
    generation); the floor is ~7x below that so only a catastrophic
    regression (e.g. reintroducing the int64 ms argsort or a full-width
    gather) trips it on a noisy shared host."""
    import time

    import spark_druid_olap_tpu as sd
    from spark_druid_olap_tpu.workloads import ssb

    ctx = sd.TPUOlapContext()
    t0 = time.perf_counter()
    ssb.register_streamed(ctx, scale=1 / 3, seed=7, workers=0)
    dt = time.perf_counter() - t0
    n = ctx.catalog.get("lineorder").num_rows
    assert n == 2_000_000
    rate = n / dt
    assert rate > 300_000, f"streamed ingest regressed: {rate:.0f} rows/s"


def test_streamed_ingest_narrow_codes_and_sorted():
    """The streamed segments store narrow dimension codes and stay
    time-sorted (zone-map pruning depends on the sort)."""
    import numpy as np

    import spark_druid_olap_tpu as sd
    from spark_druid_olap_tpu.catalog.segment import code_dtype
    from spark_druid_olap_tpu.workloads import ssb

    ctx = sd.TPUOlapContext()
    ssb.register_streamed(ctx, scale=0.02, seed=7, workers=0)
    ds = ctx.catalog.get("lineorder")
    for d in ("c_region", "d_year", "p_brand1"):
        want = code_dtype(ds.dicts[d].cardinality)
        got = ds.segments[0].dims[d].dtype
        assert got == want, (d, got, want)
    for s in ds.segments[:3]:
        t = np.asarray(s.time)[np.asarray(s.valid)]
        assert (np.diff(t) >= 0).all()
