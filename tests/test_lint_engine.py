"""Unit tests for tools/graftlint/engine.py — the interprocedural layer.

test_lint.py exercises the GL24xx/GL25xx passes end-to-end through the
fixture matrix; this file tests the DataflowEngine primitives those
passes (and `--changed`'s reverse-dependency closure) are built on:

- the canonical function index and module dependency graph,
- reverse closure (what a changed file can affect),
- thread-entry detection and reachability, including method calls
  through typed receivers (module singletons, annotated parameters),
- majority-rule lock-ownership inference,
- the forward order-taint lattice: sources, sanitizers (including the
  in-place `.sort()` form), comprehension absorption, and taint flowing
  interprocedurally through returns and keyword arguments.

The final section anchors the analyses against the shipped tree's real
idioms: the broker's sort-before-fold gather is reproduced as a CLEAN
fixture (the exemplar the GL24xx pass exists to protect) and its
arrival-order mutation as the VIOLATING twin — the regression pair for
the cluster/ fold-determinism audit this pass now automates.
"""

from lint_harness import engine_of, project_of, run_on


def _fn(project, relpath, qualname):
    return project.modules[relpath].functions[qualname]


# ---------------------------------------------------------------------------
# symbol table + module dependency graph
# ---------------------------------------------------------------------------


def test_fn_by_canonical_indexes_functions_and_methods(tmp_path):
    _, engine = engine_of(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/a.py": """
            def top():
                pass

            class C:
                def meth(self):
                    pass
        """,
    })
    idx = engine.fn_by_canonical
    assert "pkg.a.top" in idx
    assert "pkg.a.C.meth" in idx
    assert idx["pkg.a.C.meth"].qualname == "C.meth"


def test_import_graph_sees_alias_and_call_edges(tmp_path):
    _, engine = engine_of(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/leaf.py": "def helper():\n    return 1\n",
        # alias edge: from-import binds pkg.leaf.helper
        "pkg/mid.py": """
            from .leaf import helper

            def use():
                return helper()
        """,
        # call edge without a leading from-import of the symbol itself
        "pkg/top.py": """
            from . import mid

            def drive():
                return mid.use()
        """,
        "pkg/island.py": "x = 1\n",
    })
    g = engine.import_graph
    assert "pkg/leaf.py" in g["pkg/mid.py"]
    assert "pkg/mid.py" in g["pkg/top.py"]
    assert g["pkg/island.py"] == set()


def test_reverse_closure_is_transitive_and_scoped(tmp_path):
    _, engine = engine_of(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/leaf.py": "VALUE = 1\n",
        "pkg/mid.py": "from .leaf import VALUE\n\nM = VALUE\n",
        "pkg/top.py": "from .mid import M\n\nT = M\n",
        "pkg/island.py": "x = 1\n",
    })
    closure = engine.reverse_closure(["pkg/leaf.py"])
    assert closure == {"pkg/leaf.py", "pkg/mid.py", "pkg/top.py"}
    # nothing imports top: its closure is itself
    assert engine.reverse_closure(["pkg/top.py"]) == {"pkg/top.py"}
    # unknown paths pass through silently (files outside the tree)
    assert engine.reverse_closure(["nope.py"]) == set()


# ---------------------------------------------------------------------------
# thread roots + reachability
# ---------------------------------------------------------------------------

_THREADED = {
    "pkg/__init__.py": "",
    "pkg/workers.py": """
        import threading
        from concurrent.futures import ThreadPoolExecutor

        def worker():
            _shared_step()

        def _shared_step():
            pass

        def pool_task(x):
            return x

        def untouched():
            pass

        def spawn():
            threading.Thread(target=worker).start()
            with ThreadPoolExecutor() as ex:
                ex.submit(pool_task, 1)

        class Loop(threading.Thread):
            def run(self):
                self.tick()

            def tick(self):
                pass

        class Handler:
            def do_GET(self):
                pass
    """,
}


def test_thread_roots_cover_targets_submits_run_and_handlers(tmp_path):
    _, engine = engine_of(tmp_path, _THREADED)
    roots = engine.thread_roots
    assert ("pkg/workers.py", "worker") in roots
    assert ("pkg/workers.py", "pool_task") in roots
    assert ("pkg/workers.py", "Loop.run") in roots
    assert ("pkg/workers.py", "Handler.do_GET") in roots
    assert ("pkg/workers.py", "untouched") not in roots
    assert ("pkg/workers.py", "spawn") not in roots


def test_thread_reachability_closes_over_calls(tmp_path):
    project, engine = engine_of(tmp_path, _THREADED)
    assert engine.is_thread_reachable(
        _fn(project, "pkg/workers.py", "_shared_step")
    )
    assert engine.is_thread_reachable(
        _fn(project, "pkg/workers.py", "Loop.tick")
    )
    assert not engine.is_thread_reachable(
        _fn(project, "pkg/workers.py", "untouched")
    )


def test_thread_reachability_through_typed_singleton_receiver(tmp_path):
    """`REGISTRY.flush()` is invisible to the symbolic call graph (the
    receiver is a value, not a name) — the typed-receiver edges close
    the gap, across modules."""
    project, engine = engine_of(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/state.py": """
            class Registry:
                def flush(self):
                    self._drain()

                def _drain(self):
                    pass


            REGISTRY = Registry()
        """,
        "pkg/daemon.py": """
            import threading

            from .state import REGISTRY

            def beat():
                REGISTRY.flush()

            def start():
                threading.Thread(target=beat).start()
        """,
    })
    assert engine.is_thread_reachable(
        _fn(project, "pkg/state.py", "Registry.flush")
    )
    assert engine.is_thread_reachable(
        _fn(project, "pkg/state.py", "Registry._drain")
    )


# ---------------------------------------------------------------------------
# lock-ownership inference
# ---------------------------------------------------------------------------


def _cc(tmp_path, body):
    _, engine = engine_of(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/mod.py": body,
    })
    return engine.concurrency.get(("pkg.mod", "C"))


def test_ownership_majority_guarded_wins(tmp_path):
    cc = _cc(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def a(self):
                with self._lock:
                    self._n += 1

            def b(self):
                with self._lock:
                    self._n = 0

            def c(self):
                self._n = 5
    """)
    assert cc.owner == {"_n": "_lock"}


def test_ownership_tie_stays_unowned(tmp_path):
    cc = _cc(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def a(self):
                with self._lock:
                    self._n += 1

            def c(self):
                self._n = 5
    """)
    assert cc.owner == {}


def test_ownership_ignores_init_writes(tmp_path):
    """__init__ runs before the object escapes: its unguarded writes
    must not out-vote a consistently guarded steady state."""
    cc = _cc(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._n = 0
                self._n = 0

            def a(self):
                with self._lock:
                    self._n += 1
    """)
    assert cc.owner == {"_n": "_lock"}


def test_ownership_picks_majority_lock_of_two(tmp_path):
    cc = _cc(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._aux = threading.Lock()
                self._n = 0

            def a(self):
                with self._lock:
                    self._n += 1

            def b(self):
                with self._lock:
                    self._n += 1

            def c(self):
                with self._aux:
                    self._n += 1
    """)
    assert cc.owner == {"_n": "_lock"}


def test_ownership_pin_annotation_breaks_tie(tmp_path):
    """A `# graftlint: owner=<lock>` pin on an access decides a
    majority tie that would otherwise stay silently unowned."""
    cc = _cc(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def a(self):
                with self._lock:
                    self._n += 1  # graftlint: owner=_lock

            def c(self):
                self._n = 5
    """)
    assert cc.owner == {"_n": "_lock"}
    assert cc.pinned == {"_n": {"_lock"}}


def test_ownership_pin_on_line_above(tmp_path):
    cc = _cc(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def c(self):
                # graftlint: owner=_lock
                self._n = 5
    """)
    assert cc.owner == {"_n": "_lock"}


def test_ownership_pin_overrides_majority(tmp_path):
    """An explicit pin beats the heuristic: the annotation names the
    convention even when most writes sit under another lock."""
    cc = _cc(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._aux = threading.Lock()
                self._n = 0

            def a(self):
                with self._lock:
                    self._n += 1

            def b(self):
                with self._lock:
                    self._n += 1

            def c(self):
                with self._aux:
                    self._n += 1  # graftlint: owner=_aux
    """)
    assert cc.owner == {"_n": "_aux"}
    assert "_aux" in cc.lock_attrs


def test_ownership_conflicting_pins_fall_back_to_majority(tmp_path):
    cc = _cc(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._aux = threading.Lock()
                self._n = 0

            def a(self):
                with self._lock:
                    self._n += 1  # graftlint: owner=_lock

            def c(self):
                self._n = 5  # graftlint: owner=_aux
    """)
    # two different pins cancel; majority (1 guarded vs 1 unguarded)
    # ties, so the field stays unowned
    assert cc.owner == {}


# ---------------------------------------------------------------------------
# order-taint lattice
# ---------------------------------------------------------------------------


def _hits(tmp_path, body, fn="f"):
    project, engine = engine_of(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/mod.py": body,
    })
    return engine.taint().analyze(_fn(project, "pkg/mod.py", fn))


def test_taint_through_callee_return(tmp_path):
    hits = _hits(tmp_path, """
        from concurrent.futures import as_completed

        def _collect(futs):
            return [f.result() for f in as_completed(futs)]

        def f(engine, q, ds, futs):
            state = None
            for r in _collect(futs):
                state = engine.merge_groupby_states(q, ds, state, r)
            return state
    """)
    assert {h.kind for h in hits} == {"loop-order"}
    assert any("as_completed" in l for h in hits for l in h.labels)


def test_taint_through_callee_kwargs_to_sink(tmp_path):
    hits = _hits(tmp_path, """
        from concurrent.futures import as_completed

        def _fold(engine, q, ds, items=None):
            state = None
            for r in items:
                state = engine.merge_sketch_states(q, ds, state, r)
            return state

        def f(engine, q, ds, futs):
            rs = [x.result() for x in as_completed(futs)]
            return _fold(engine, q, ds, items=rs)
    """)
    assert {h.kind for h in hits} == {"interprocedural"}
    assert hits[0].via == "pkg.mod._fold"


def test_sorted_sanitizes_the_gather(tmp_path):
    assert _hits(tmp_path, """
        from concurrent.futures import as_completed

        def f(engine, q, ds, futs):
            rs = [x.result() for x in as_completed(futs)]
            state = None
            for r in sorted(rs, key=lambda t: t[0]):
                state = engine.merge_groupby_states(q, ds, state, r)
            return state
    """) == []


def test_inplace_sort_sanitizes_the_receiver(tmp_path):
    assert _hits(tmp_path, """
        import os

        def f(engine, q, ds, root):
            names = list(os.listdir(root))
            names.sort()
            state = None
            for n in names:
                state = engine.merge_groupby_states(q, ds, state, n)
            return state
    """) == []


def test_set_comprehension_is_itself_a_source(tmp_path):
    hits = _hits(tmp_path, """
        def f(engine, q, ds, cols):
            state = None
            for c in {c for c in cols}:
                state = engine.merge_groupby_states(q, ds, state, c)
            return state
    """)
    assert {h.kind for h in hits} == {"loop-order"}


def test_dict_comprehension_absorbs_order_taint(tmp_path):
    """Rebuilding into a dict keyed deterministically gives insertion
    order — still arrival order here, but iterating a dict is NOT a
    source, so the absorbed value folds clean (CPython dicts are
    insertion-ordered; flagging every dict walk would bury the signal)."""
    assert _hits(tmp_path, """
        def f(engine, q, ds, by_key):
            state = None
            for k, v in by_key.items():
                state = engine.merge_groupby_states(q, ds, state, v)
            return state
    """) == []


def test_param_taint_never_fires_locally(tmp_path):
    """A fold over a plain parameter is the CALLEE's half of an
    interprocedural finding — it must not self-report (the summary
    carries it to call sites that pass tainted data)."""
    assert _hits(tmp_path, """
        def f(engine, q, ds, items):
            state = None
            for r in items:
                state = engine.merge_groupby_states(q, ds, state, r)
            return state
    """) == []


def test_summary_records_param_to_sink_and_return_taint(tmp_path):
    project, engine = engine_of(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/mod.py": """
            from concurrent.futures import as_completed

            def sink_half(engine, q, ds, items):
                state = None
                for r in items:
                    state = engine.merge_groupby_states(q, ds, state, r)
                return state

            def tainted_return(futs):
                return [f.result() for f in as_completed(futs)]
        """,
    })
    taint = engine.taint()
    s = taint.summary(_fn(project, "pkg/mod.py", "sink_half"))
    assert "items" in s.params_to_sink
    s = taint.summary(_fn(project, "pkg/mod.py", "tainted_return"))
    assert s.returns_tainted
    assert any("as_completed" in l for l in s.return_labels)


# ---------------------------------------------------------------------------
# regression anchors: the shipped tree's real idioms, both halves
# ---------------------------------------------------------------------------

# the broker's gather (cluster/broker.py): collect in completion order,
# fold in sorted assignment order — the exemplar GL24xx protects.  The
# violating twin folds at arrival; one edit distance from the real code.
_BROKER_GATHER_CLEAN = {
    "spark_druid_olap_tpu/cluster/mini_broker.py": """
        from concurrent.futures import as_completed

        def gather(engine, q, ds, futs, expect_version):
            results = []
            for fut in as_completed(futs):
                results.append(fut.result())
            state = None
            for r in sorted(results, key=lambda t: t["chain"]):
                if r["version"] != expect_version:
                    continue
                state = engine.merge_groupby_states(
                    q, ds, state, r["state"]
                )
            return state
    """,
}

_BROKER_GATHER_ARRIVAL = {
    "spark_druid_olap_tpu/cluster/mini_broker.py": """
        from concurrent.futures import as_completed

        def gather(engine, q, ds, futs, expect_version):
            state = None
            for fut in as_completed(futs):
                r = fut.result()
                if r["version"] != expect_version:
                    continue
                state = engine.merge_groupby_states(
                    q, ds, state, r["state"]
                )
            return state
    """,
}


def test_broker_gather_exemplar_is_clean(tmp_path):
    res = run_on(
        tmp_path, _BROKER_GATHER_CLEAN, passes=["fold-determinism"]
    )
    assert res.new == [], [f.render() for f in res.new]


def test_broker_gather_arrival_order_twin_is_flagged(tmp_path):
    res = run_on(
        tmp_path, _BROKER_GATHER_ARRIVAL, passes=["fold-determinism"]
    )
    assert {f.code for f in res.new} == {"GL2401"}
    assert "as_completed" in res.new[0].message


def test_breaker_style_guarded_class_is_clean(tmp_path):
    """resilience.py's CircuitBreaker shape: every state transition
    under the lock, public snapshot property — the GL25xx clean anchor."""
    res = run_on(tmp_path, {
        "spark_druid_olap_tpu/mini_resilience.py": """
            import threading

            class CircuitBreaker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._state = "closed"
                    self._failures = 0

                def record_failure(self):
                    with self._lock:
                        self._failures += 1
                        if self._failures >= 3:
                            self._state = "open"

                def record_ok(self):
                    with self._lock:
                        self._failures = 0
                        self._state = "closed"

                @property
                def state(self):
                    with self._lock:
                        return self._state
        """,
    }, passes=["shared-state-races"])
    assert res.new == [], [f.render() for f in res.new]


def test_breaker_style_off_lock_transition_is_flagged(tmp_path):
    res = run_on(tmp_path, {
        "spark_druid_olap_tpu/mini_resilience.py": """
            import threading

            class CircuitBreaker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._failures = 0

                def record_failure(self):
                    with self._lock:
                        self._failures += 1

                def record_ok(self):
                    with self._lock:
                        self._failures = 0

                def reset_unsafely(self):
                    self._failures = 0
        """,
    }, passes=["shared-state-races"])
    assert {f.code for f in res.new} == {"GL2501"}
    assert "_lock" in res.new[0].message


def test_pragma_and_allow_config_suppress_races(tmp_path):
    files = {
        "spark_druid_olap_tpu/mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def a(self):
                    with self._lock:
                        self._n += 1

                def b(self):
                    with self._lock:
                        self._n += 1

                def fast_path(self):
                    self._n = 0  # graftlint: disable=shared-state-races -- benchmark-only reset
        """,
    }
    res = run_on(tmp_path, files, passes=["shared-state-races"])
    assert res.new == [], [f.render() for f in res.new]
    # same code without the pragma, allow-listed via config instead
    files_plain = {
        "spark_druid_olap_tpu/mod.py": files[
            "spark_druid_olap_tpu/mod.py"
        ].replace(
            "  # graftlint: disable=shared-state-races -- "
            "benchmark-only reset",
            "",
        ),
    }
    res = run_on(
        tmp_path / "allow", files_plain, passes=["shared-state-races"],
        config_overrides={"shared-state-races": {"allow": [
            ["spark_druid_olap_tpu.mod", "C", "_n"],
        ]}},
    )
    assert res.new == [], [f.render() for f in res.new]


# ---------------------------------------------------------------------------
# effect-summary layer + protocol automata (GL28xx/GL29xx substrate)
# ---------------------------------------------------------------------------

_EFFECT_TREE = {
    "pkg/__init__.py": "",
    "pkg/res.py": "def checkpoint(site):\n    pass\n",
    "pkg/wal.py": """
        from .res import checkpoint

        class WriteAheadLog:
            def append(self, ds):
                checkpoint("wal.journal_write")
                checkpoint("wal.post_fsync_pre_publish")
                self.catalog.put(ds)
                return True
    """,
    "pkg/gate.py": """
        from .res import checkpoint

        class Gate:
            def run(self, res, q):
                if not res.admission.acquire():
                    return None
                try:
                    checkpoint("serve.lane_execute")
                    return self._work(q)
                finally:
                    res.admission.release()

            def leaky(self, res, q):
                res.admission.acquire()
                checkpoint("serve.lane_execute")
                res.admission.release()

            def locked(self):
                self._lock.acquire()
                self._lock.release()
    """,
}


def _effect_seqs(eff, fi):
    return {
        (p.exit, tuple((e.kind, e.res) for e in p.effects))
        for p in eff.paths(fi)
    }


def test_effect_paths_order_sites_and_exception_splits(tmp_path):
    """The enumerated paths carry ordered effect sequences with one
    raise variant per may-raise point, each holding the PRE-commit
    state of the failing step (an injected fault means the step did
    not happen)."""
    project, engine = engine_of(tmp_path, _EFFECT_TREE)
    eff = engine.effects({})
    fi = project.modules["pkg/wal.py"].functions["WriteAheadLog.append"]
    seqs = _effect_seqs(eff, fi)
    assert ("return", (
        ("journal", "wal.journal_write"),
        ("fsync", "wal.post_fsync_pre_publish"),
        ("publish", "self.catalog.put"),
    )) in seqs
    # checkpoint raises carry pre-site state; the publish raise carries
    # journal+fsync (durable-but-unpublished: the GL2803 window)
    assert ("raise", ()) in seqs
    assert ("raise", (("journal", "wal.journal_write"),)) in seqs
    assert ("raise", (
        ("journal", "wal.journal_write"),
        ("fsync", "wal.post_fsync_pre_publish"),
    )) in seqs


def test_effect_finally_balances_every_raise_edge(tmp_path):
    project, engine = engine_of(tmp_path, _EFFECT_TREE)
    eff = engine.effects({})
    mod = project.modules["pkg/gate.py"]
    # try/finally: every exit (return AND raise) releases the slot
    for p in eff.paths(mod.functions["Gate.run"]):
        kinds = [e.kind for e in p.effects]
        assert kinds == ["acquire", "release"], (p.exit, kinds)
    # no finally: the checkpoint's raise edge leaks the open acquire
    leaky = _effect_seqs(eff, mod.functions["Gate.leaky"])
    assert ("raise", (("acquire", "res.admission"),)) in leaky
    # finally_paths exposes the finalizer's own effect paths (GL2903)
    fps = eff.finally_paths(mod.functions["Gate.run"])
    assert len(fps) == 1
    _node, fpaths = fps[0]
    assert {e.kind for p in fpaths for e in p.effects} == {"release"}


def test_lockish_receivers_are_not_slot_resources(tmp_path):
    """`self._lock.acquire()` is lock discipline (GL5xx/GL25xx), not a
    slot/lane/span resource — the effect layer must not model it."""
    project, engine = engine_of(tmp_path, _EFFECT_TREE)
    eff = engine.effects({})
    fi = project.modules["pkg/gate.py"].functions["Gate.locked"]
    assert _effect_seqs(eff, fi) == {("return", ())}


def test_effects_analysis_is_memoized_per_config(tmp_path):
    _, engine = engine_of(tmp_path, _EFFECT_TREE)
    a = engine.effects({"summary_depth": 3})
    b = engine.effects({"summary_depth": 3})
    c = engine.effects({"summary_depth": 2})
    assert a is b and a is not c


def test_protocol_automaton_static_run_and_whole_or_absent(tmp_path):
    """The durable-publish machine flags a raise edge inside the
    post-fsync pre-publish window — unless the function's canonical
    name carries the whole-or-absent exemption."""
    from tools.graftlint.engine import ProtocolAutomaton
    from tools.graftlint.passes.durability_protocol import (
        DURABLE_PUBLISH,
    )

    project, engine = engine_of(tmp_path, _EFFECT_TREE)
    eff = engine.effects({})
    fi = project.modules["pkg/wal.py"].functions["WriteAheadLog.append"]
    a = ProtocolAutomaton(dict(DURABLE_PUBLISH))
    canon = "pkg.wal.WriteAheadLog.append"
    assert a.matches(canon)
    assert not a.matches("pkg.wal.WriteAheadLog.replay")
    findings = [
        (code, msg)
        for p in eff.paths(fi)
        for _n, code, msg in a.run_static(p, canon, frozenset())
    ]
    assert [c for c, _ in findings] == ["GL2803"]
    exempt = [
        code
        for p in eff.paths(fi)
        for _n, code, _m in a.run_static(p, canon, frozenset({canon}))
    ]
    assert exempt == []
