"""Worker for the TRUE two-process multi-host test (VERDICT r2 #4).

Run as:  python multihost_worker.py <port> <process_id> <num_processes> <out>

Forms a real `jax.distributed` runtime over localhost (CPU backend, 4
virtual devices per process -> 8 global), builds the hybrid DCNxICI mesh,
and runs ONE distributed GroupBy whose shards were placed with the
multi-process `put_sharded` path.  The parent asserts parity against a
single-process run of the same query."""

import json
import sys


def main():
    port, pid, nproc, outpath = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
    )
    # rendezvous FIRST — before any jax call touches the backend
    from spark_druid_olap_tpu.parallel import multihost

    ok = multihost.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert ok, "initialize() did not join the distributed runtime"

    import jax
    import numpy as np

    assert jax.process_count() == nproc, jax.process_count()

    from spark_druid_olap_tpu.catalog.segment import build_datasource
    from spark_druid_olap_tpu.models.aggregations import Count, DoubleSum
    from spark_druid_olap_tpu.models.dimensions import DimensionSpec
    from spark_druid_olap_tpu.models.query import GroupByQuery
    from spark_druid_olap_tpu.parallel.distributed import DistributedEngine

    mesh = multihost.hybrid_mesh()
    info = multihost.process_info()

    # deterministic data — every process derives the same global catalog
    rng = np.random.default_rng(3)
    n = 8192
    g = rng.integers(0, 7, n).astype(np.int64)
    v = rng.random(n).astype(np.float32)
    ds = build_datasource(
        "mh", {"g": g, "v": v},
        dimension_cols=["g"], metric_cols=["v"], rows_per_segment=1024,
    )
    q = GroupByQuery(
        datasource="mh",
        dimensions=(DimensionSpec("g"),),
        aggregations=(DoubleSum("s", "v"), Count("n")),
    )
    eng = DistributedEngine(mesh=mesh)
    out = eng.execute(q, ds)
    res = {
        "process": pid,
        "info": info,
        "mesh_shape": {k: int(s) for k, s in mesh.shape.items()},
        "rows": sorted(
            [str(r["g"]), round(float(r["s"]), 4), int(r["n"])]
            for _, r in out.iterrows()
        ),
    }

    # sketch-state merges across the REAL process boundary (VERDICT r3 #8):
    # HLL register-max, theta hash-union, and quantile sample-union all
    # fold over DCNxICI collectives here; finalized estimates are exact
    # integers / deterministic floats, so equality with the single-process
    # run means the merged register/sample states agree
    from spark_druid_olap_tpu.models.aggregations import (
        HyperUnique,
        QuantileFromSketch,
        QuantilesSketch,
        ThetaSketch,
    )

    ksk = rng.integers(0, 3000, n).astype(np.int64)
    lat = (rng.gamma(2.0, 10.0, n)).astype(np.float32)
    ds2 = build_datasource(
        "mhsk", {"g": g, "v": v, "k": ksk, "lat": lat},
        dimension_cols=["g"], metric_cols=["v", "k", "lat"],
        rows_per_segment=1024,
    )
    q2 = GroupByQuery(
        datasource="mhsk",
        dimensions=(DimensionSpec("g"),),
        aggregations=(
            HyperUnique("hll", "k"),
            ThetaSketch("theta", "k"),
            QuantilesSketch("qn", "lat"),
        ),
        post_aggregations=(QuantileFromSketch("p50", "qn", 0.5),),
    )
    out2 = eng.execute(q2, ds2)
    res["sketch_rows"] = sorted(
        [
            str(r["g"]), int(r["hll"]), int(r["theta"]), int(r["qn"]),
            round(float(r["p50"]), 5),
        ]
        for _, r in out2.iterrows()
    )

    # round-5: the HIGH-CARDINALITY sparse tier across the real process
    # boundary — per-device sort-compaction, then the all_gather +
    # merge_sparse_states fold rides the DCNxICI collectives (the same
    # data-axis merge the dense psum above crosses).  rng draws stay in
    # lockstep with the parent's replay (g, v, ksk, lat, THEN these).
    from spark_druid_olap_tpu.catalog.segment import DimensionDict

    da = db = 300  # combined domain 90K >> SPARSE_SLOTS
    pairs = rng.choice(da * db, size=800, replace=False)
    pick = pairs[rng.integers(0, 800, n)]
    ds3 = build_datasource(
        "mhhc",
        {
            "a": (pick // db).astype(np.int64),
            "b": (pick % db).astype(np.int64),
            "v": v,
        },
        dimension_cols=["a", "b"], metric_cols=["v"],
        rows_per_segment=2048,
        dicts={
            "a": DimensionDict(values=tuple(range(da))),
            "b": DimensionDict(values=tuple(range(db))),
        },
    )
    q3 = GroupByQuery(
        datasource="mhhc",
        dimensions=(DimensionSpec("a"), DimensionSpec("b")),
        aggregations=(Count("n"), DoubleSum("s", "v")),
    )
    sp_eng = DistributedEngine(mesh=mesh, strategy="sparse")
    out3 = sp_eng.execute(q3, ds3)
    assert sp_eng.last_metrics.strategy == "sparse"
    res["sparse_rows"] = sorted(
        [str(r["a"]), str(r["b"]), int(r["n"]), round(float(r["s"]), 4)]
        for _, r in out3.iterrows()
    )

    with open(outpath, "w") as f:
        json.dump(res, f)
    print("WORKER_OK", pid)


if __name__ == "__main__":
    main()
