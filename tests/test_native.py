"""Native (C++) ingest layer: CSV decode + dictionary encoding.

Differential tests against the pandas/python fallback paths — the native
layer must be a bit-identical accelerator, never a semantic fork.  Skipped
wholesale when no C++ toolchain is present (the framework must work without
it)."""

import numpy as np
import pandas as pd
import pytest

from spark_druid_olap_tpu import native
from spark_druid_olap_tpu.catalog.segment import DimensionDict

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


@pytest.fixture()
def csv_path(tmp_path):
    df = pd.DataFrame(
        {
            "region": ["EU", "US", "ASIA", "EU", "US", "EU"],
            "city": ['a "quoted" one', "b,with,commas", "", "plain", "", "z"],
            "qty": [1, 2, 3, 4, 5, 6],
            "price": [1.5, 2.25, 0.0, -3.5, 1e6, 0.125],
            "maybe_int": ["1", "", "3", "4", "", "6"],
        }
    )
    p = tmp_path / "t.csv"
    df.to_csv(p, index=False)
    return str(p)


def test_read_csv_matches_pandas(csv_path):
    from spark_druid_olap_tpu.native.csv_decode import read_csv

    got = read_csv(csv_path)
    want = pd.read_csv(csv_path)

    assert set(got) == set(want.columns)
    np.testing.assert_array_equal(got["qty"], want["qty"].values)
    assert got["qty"].dtype == np.int64
    np.testing.assert_allclose(got["price"], want["price"].values)
    # ints with nulls promote to double + NaN (pandas parity)
    assert got["maybe_int"].dtype == np.float64
    np.testing.assert_array_equal(
        np.isnan(got["maybe_int"]), want["maybe_int"].isna().values
    )
    np.testing.assert_allclose(
        got["maybe_int"][~np.isnan(got["maybe_int"])],
        want["maybe_int"].dropna().values,
    )
    # strings: None where pandas has NaN, equal values elsewhere
    for c in ("region", "city"):
        w = want[c].values
        for g, ww in zip(got[c], w):
            if isinstance(ww, float) and np.isnan(ww):
                assert g is None
            else:
                assert g == ww


def test_read_csv_encoded_dict_contract(csv_path):
    from spark_druid_olap_tpu.native.csv_decode import read_csv_encoded

    cols, dicts = read_csv_encoded(csv_path)
    # dictionary matches the python DimensionDict for the same data
    raw = pd.read_csv(csv_path)["region"].values
    ref = DimensionDict.build(list(raw))
    assert dicts["region"].values == ref.values
    np.testing.assert_array_equal(cols["region"], ref.encode(list(raw)))
    # empty fields are null codes
    city = cols["city"]
    assert (city == -1).sum() == 2


def test_register_table_from_csv_native(tmp_path):
    import spark_druid_olap_tpu as sd

    df = pd.DataFrame(
        {
            "flag": ["A", "B", "A", "C", "B", "A"],
            "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0],
        }
    )
    p = tmp_path / "t.csv"
    df.to_csv(p, index=False)

    ctx = sd.TPUOlapContext()
    ctx.register_table("t", str(p), dimensions=["flag"], metrics=["v"])
    out = ctx.sql("SELECT flag, sum(v) AS s FROM t GROUP BY flag ORDER BY flag")
    want = df.groupby("flag", as_index=False)["v"].sum()
    assert list(out["flag"]) == list(want["flag"])
    np.testing.assert_allclose(out["s"], want["v"].values)


def test_register_table_csv_schema_inference(tmp_path):
    import spark_druid_olap_tpu as sd

    df = pd.DataFrame(
        {"d": ["x", "y", "x"], "m": [1.5, 2.5, 3.5]}
    )
    p = tmp_path / "t2.csv"
    df.to_csv(p, index=False)
    ctx = sd.TPUOlapContext()
    ds = ctx.register_table("t2", str(p))
    kinds = {c.name: c.kind for c in ds.columns}
    assert kinds["d"] == "dimension"
    assert kinds["m"] == "metric"


def test_encode_strings_matches_python():
    from spark_druid_olap_tpu.native.csv_decode import encode_strings

    vals = ["pear", "apple", None, "apple", "banana", None, "pear"]
    codes, uniq = encode_strings(vals)
    ref = DimensionDict.build(vals)
    assert uniq == ref.values
    np.testing.assert_array_equal(codes, ref.encode(vals))


def test_caller_dict_wins_by_reencoding(tmp_path):
    """A caller-supplied dictionary must re-encode raw values — native rank
    codes (ranks over the FILE's domain) must never be reinterpreted under a
    different domain."""
    import spark_druid_olap_tpu as sd

    df = pd.DataFrame({"region": ["EU", "US", "EU"], "v": [1.0, 2.0, 4.0]})
    p = tmp_path / "r.csv"
    df.to_csv(p, index=False)
    shared = DimensionDict(values=("ASIA", "EU", "US"))  # wider shared domain
    ctx = sd.TPUOlapContext()
    ctx.register_table(
        "r", str(p), dimensions=["region"], metrics=["v"],
        dicts={"region": shared},
    )
    out = ctx.sql("SELECT region, sum(v) AS s FROM r GROUP BY region ORDER BY region")
    assert list(out["region"]) == ["EU", "US"]
    np.testing.assert_allclose(out["s"], [5.0, 2.0])


def test_string_time_column_parses_to_millis(tmp_path):
    import spark_druid_olap_tpu as sd

    df = pd.DataFrame(
        {
            "d": ["1992-01-01", "1992-01-02", "1992-01-01", "1992-01-03"],
            "v": [1.0, 2.0, 4.0, 8.0],
        }
    )
    p = tmp_path / "tt.csv"
    df.to_csv(p, index=False)
    ctx = sd.TPUOlapContext()
    ds = ctx.register_table("tt", str(p), metrics=["v"], time_column="d")
    lo, hi = ds.interval()
    assert lo == np.datetime64("1992-01-01", "ms").astype(np.int64)
    out = ctx.sql(
        "SELECT sum(v) AS s FROM tt WHERE d >= '1992-01-02'"
    )
    np.testing.assert_allclose(out["s"], [10.0])


def test_ragged_csv_falls_back_to_pandas(tmp_path):
    """Rows with missing trailing fields: the strict C parser rejects them,
    ingest must fall back to pandas rather than raise at registration."""
    import spark_druid_olap_tpu as sd

    p = tmp_path / "rag.csv"
    p.write_text("a,b\nx,1\ny\n")
    ctx = sd.TPUOlapContext()
    ds = ctx.register_table("rag", str(p), dimensions=["a"], metrics=["b"])
    assert ds.num_rows == 2


def test_quoted_multiline_field(tmp_path):
    p = tmp_path / "m.csv"
    p.write_text('a,b\n"line1\nline2",3\nplain,4\n')
    from spark_druid_olap_tpu.native.csv_decode import read_csv

    got = read_csv(str(p))
    assert list(got["a"]) == ["line1\nline2", "plain"]
    np.testing.assert_array_equal(got["b"], [3, 4])


def test_many_short_escaped_quotes(tmp_path):
    """Arena stability: many short quoted-escaped fields must not corrupt
    earlier fields when the arena grows (dangling-SSO regression)."""
    from spark_druid_olap_tpu.native.csv_decode import read_csv

    rows = [f'"v""{i:02d}"' for i in range(64)]
    p = tmp_path / "esc.csv"
    p.write_text("a\n" + "\n".join(rows) + "\n")
    got = read_csv(str(p))
    assert list(got["a"]) == [f'v"{i:02d}' for i in range(64)]


def test_na_sentinels_match_pandas(tmp_path):
    """pandas' default na_values must read as nulls, keeping type inference
    identical to the pd.read_csv fallback."""
    from spark_druid_olap_tpu.native.csv_decode import read_csv

    p = tmp_path / "na.csv"
    p.write_text("x,v,s\na,1.5,foo\nb,NA,NaN\nc,3.0,null\n")
    got = read_csv(str(p))
    want = pd.read_csv(p)
    assert str(want["v"].dtype) == "float64"
    assert got["v"].dtype == np.float64
    np.testing.assert_array_equal(np.isnan(got["v"]), want["v"].isna().values)
    assert list(got["s"]) == ["foo", None, None]
