"""graftsan (ISSUE 18): static↔runtime contract agreement.

Three claims pinned here:

1. **The shipped tree is clean.**  The serve+ingest and cluster hammers
   run under `SDOL_SANITIZE=1` with every layer armed — lock witness,
   fold-order recorder, schedule explorer — and report ZERO violations
   and ZERO ownership divergences against the committed
   `graftsan_contracts.json`.
2. **The sanitizer actually catches breaches.**  A seeded fixture
   injects a known off-lock write (and an off-lock container mutate, and
   an out-of-order fold, and an aliased ⊕) and each is caught
   deterministically, with the replay seed in the failure message.
3. **Disabled means free.**  With no sanitizer installed the probe
   count is exactly zero on the cached-program path and every contract
   class runs its original, unwrapped bytecode.
"""

import json
import os
import threading

import numpy as np
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu import resilience
from spark_druid_olap_tpu.exec.pipeline import CanonicalFold
from tools import graftsan
from tools.graftsan.sanitizer import Sanitizer
from tools.graftsan.scheduler import ScheduleExplorer
from tools.graftsan.witness import FieldWitness, WitnessLock

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONTRACTS_PATH = os.path.join(ROOT, "graftsan_contracts.json")


def _cols(n=2000, seed=11):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(
            np.array(["NY", "SF", "LA", "CHI"], dtype=object), n
        ),
        "qty": rng.integers(1, 9, n).astype(np.int64),
        "rev": rng.random(n).astype(np.float32),
    }


@pytest.fixture()
def armed(monkeypatch):
    """Repo contract table, every layer installed, restored on exit."""
    monkeypatch.setenv(graftsan.ENV_ARM, "1")
    san = graftsan.install(
        contracts_path=CONTRACTS_PATH, root=ROOT, seed=0
    )
    try:
        yield san
    finally:
        graftsan.uninstall()


def _run_threads(workers):
    ts = [
        threading.Thread(target=fn, name=f"san-hammer-{i}")
        for i, fn in enumerate(workers)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


# -- 1. shipped-tree agreement ------------------------------------------------


def test_serve_ingest_hammer_zero_violations_zero_divergences(armed):
    san = armed
    # the context is built INSIDE the sanitized window so every lock it
    # allocates is a WitnessLock and held-sets are exact, not raw-lock
    # best-effort
    ctx = sd.TPUOlapContext(sd.SessionConfig.load_calibrated())
    ctx.register_table(
        "ev", _cols(), dimensions=["city"], metrics=["qty", "rev"]
    )
    errors = []

    def worker(wid):
        def run():
            try:
                for _ in range(3):
                    ctx.sql(
                        "SELECT city, SUM(rev) AS r, COUNT(*) AS c "
                        "FROM ev GROUP BY city"
                    )
                    if wid % 2 == 0:
                        ctx.append_rows("ev", _cols(n=1, seed=wid))
                    else:
                        # grouping-sets expansion crosses the
                        # arm_set_collection path the static tier
                        # could not see through the untyped local
                        ctx.sql(
                            "SELECT city, SUM(qty) AS q "
                            "FROM ev GROUP BY CUBE (city)"
                        )
            except Exception as e:  # pragma: no cover - failure detail
                errors.append(e)

        return run

    _run_threads([worker(w) for w in range(4)])

    assert errors == []
    assert san.violations == []
    assert graftsan.divergence_report(san) == []
    # the run must have actually witnessed the tree, not vacuously passed
    assert sum(w.writes for w in san.witness.records.values()) > 0
    assert san.foldorder.fold_calls > 0
    assert san.scheduler.probes > 0


def test_cluster_hammer_zero_violations_zero_divergences(armed, tmp_path):
    from spark_druid_olap_tpu.cluster import ClusterClient, HistoricalNode

    san = armed
    ctx = sd.TPUOlapContext(sd.SessionConfig(storage_dir=str(tmp_path)))
    ctx.register_table(
        "ev", _cols(seed=3), dimensions=["city"], metrics=["qty", "rev"],
        rows_per_segment=500,
    )
    nodes = {}
    client = None
    try:
        for i in range(2):
            h = HistoricalNode(f"h{i}", str(tmp_path)).start()
            nodes[h.node_id] = h
        client = ClusterClient(
            ctx, nodes={nid: h.url for nid, h in nodes.items()},
            replication=2,
        ).attach()
        errors = []

        def worker(wid):
            def run():
                try:
                    for i in range(2):
                        # LIMIT varies per call to dodge the result
                        # cache and force real scatter/gather merges
                        ctx.sql(
                            "SELECT city, sum(qty) AS q, count(*) AS n "
                            "FROM ev GROUP BY city ORDER BY city "
                            f"LIMIT {100 + 10 * wid + i}"
                        )
                except Exception as e:  # pragma: no cover
                    errors.append(e)

            return run

        _run_threads([worker(w) for w in range(3)])
        assert errors == []
    finally:
        if client is not None:
            client.close()
        for h in nodes.values():
            h.shutdown()

    assert san.violations == []
    assert graftsan.divergence_report(san) == []
    # scatter/gather must have exercised the pairwise ⊕ sinks
    assert sum(
        rec["calls"] for rec in san.foldorder.sinks.values()
    ) > 0


# -- 2. injected breaches are caught ------------------------------------------


class _Racy:
    """Test-local contract class: `state` and `items` owned by _lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.state = 0          # construction writes are exempt
        self.items = {}

    def bump_locked(self):
        with self._lock:
            self.state += 1

    def bump_racy(self):
        self.state += 1


def _racy_contracts():
    mod = _Racy.__module__
    return {
        "version": 1,
        "package": "tests",
        "lock_ownership": [
            {"module": mod, "class": "_Racy", "field": f,
             "lock": "_lock", "source": "annotation"}
            for f in ("state", "items")
        ],
        "lock_attrs": {f"{mod}._Racy": ["_lock"]},
        "fold_sinks": [],
        "thread_roots": [],
        "allow_sites": [],
    }


@pytest.fixture()
def racy_san():
    san = Sanitizer(_racy_contracts(), ROOT, seed=7)
    san.install(schedule=False)
    try:
        yield san
    finally:
        san.uninstall()


def test_injected_off_lock_write_caught_with_replay_seed(racy_san):
    r = _Racy()            # constructor writes: no violation
    r.bump_locked()        # owned write under the owning lock: clean
    assert racy_san.violations == []

    with pytest.raises(graftsan.SanitizerViolation) as ei:
        r.bump_racy()
    msg = str(ei.value)
    assert "off-lock-write" in msg
    assert "_Racy.state" in msg
    # the failure replays exactly: the message carries the seed
    assert f"{graftsan.ENV_SEED}=7" in msg
    assert racy_san.violations[-1]["seed"] == 7
    assert racy_san.violations[-1]["snippet"] == "self.state += 1"


def test_injected_off_lock_container_mutate_caught(racy_san):
    r = _Racy()
    with r._lock:
        r.items["a"] = 1   # owned dict, mutated under the lock: clean
    assert racy_san.violations == []
    with pytest.raises(graftsan.SanitizerViolation) as ei:
        r.items["b"] = 2   # same mutation off-lock: GL2502's shape, live
    assert "off-lock-mutate" in str(ei.value)


def test_witness_lock_tracks_owner_and_reentrancy(racy_san):
    r = _Racy()
    assert isinstance(r._lock, WitnessLock)
    assert not r._lock.held_by_me()
    with r._lock:
        assert r._lock.held_by_me()
    assert not r._lock.held_by_me()


class _BuggyFold:
    """CanonicalFold's interface, draining in DESCENDING batch order."""

    def __init__(self, fold):
        self._fold = fold
        self._pending = {}
        self._next = 0

    def add(self, bi, value):
        self._pending[bi] = value

    def drain(self):
        for bi in sorted(self._pending, reverse=True):
            self._fold(self._pending.pop(bi))


class _Sink:
    def merge_groupby_states(self, q, ds, a, b):
        return {"v": a["v"] + b["v"]}


def _fold_contracts():
    mod = _BuggyFold.__module__
    return {
        "version": 1,
        "package": "tests",
        "lock_ownership": [],
        "lock_attrs": {},
        "fold_sinks": [
            {"name": "spark_druid_olap_tpu.exec.pipeline.CanonicalFold",
             "kind": "canonical-fold", "order": "ascending-batch-index"},
            {"name": f"{mod}._BuggyFold",
             "kind": "canonical-fold", "order": "ascending-batch-index"},
            {"name": "merge_groupby_states", "kind": "merge-sink",
             "order": "canonical-chain", "defined_in": [[mod, "_Sink"]]},
        ],
        "thread_roots": [],
        "allow_sites": [],
    }


@pytest.fixture()
def fold_san():
    san = Sanitizer(_fold_contracts(), ROOT, seed=5)
    san.install(schedule=False)
    try:
        yield san
    finally:
        san.uninstall()


def test_fold_recorder_passes_canonical_fold_and_fails_buggy(fold_san):
    # the REAL CanonicalFold under out-of-order dispatch: recorder
    # observes ascending folds, no violation
    out = []
    cf = CanonicalFold(out.append)
    cf.add(2, ["c"])
    cf.add(0, ["a"])
    cf.add(1, ["b"])
    cf.drain()
    assert out == [["a"], ["b"], ["c"]]
    assert fold_san.violations == []
    assert fold_san.foldorder.fold_calls >= 4

    # the descending drain is caught, seed in the message
    bf = _BuggyFold(lambda v: None)
    bf.add(0, ["x"])
    bf.add(1, ["y"])
    bf.add(2, ["z"])
    with pytest.raises(graftsan.SanitizerViolation) as ei:
        bf.drain()
    msg = str(ei.value)
    assert "fold-order" in msg and f"{graftsan.ENV_SEED}=5" in msg


def test_merge_sink_aliasing_caught_and_shapes_stamped(fold_san):
    s = _Sink()
    a, b = {"v": 1.0}, {"v": 2.0}
    ab = s.merge_groupby_states(None, None, a, b)       # leaf⊕leaf
    s.merge_groupby_states(None, None, ab, {"v": 3.0})  # product⊕leaf
    with pytest.raises(graftsan.SanitizerViolation) as ei:
        s.merge_groupby_states(None, None, a, a)
    assert "fold-aliasing" in str(ei.value)
    shapes = fold_san.foldorder.sinks["merge_groupby_states"]["shapes"]
    assert shapes.get("leaf⊕leaf", 0) >= 1
    assert shapes.get("product⊕leaf", 0) >= 1


# -- 3. divergence report directions ------------------------------------------


def _report_san():
    doc = {
        "version": 1, "package": "tests",
        "lock_ownership": [
            {"module": "m", "class": "C", "field": "owned_f",
             "lock": "_lock", "source": "majority"},
        ],
        "lock_attrs": {}, "fold_sinks": [], "thread_roots": [],
        "allow_sites": [],
    }
    return Sanitizer(doc, ROOT)  # never installed: report logic only


def _witness(writes, by_sig, unknown=0):
    w = FieldWitness()
    w.writes = writes
    w.by_sig = dict(by_sig)
    w.unknown = unknown
    return w


def test_divergence_static_owned_never_locked():
    san = _report_san()
    san.witness.records[("m.C", "owned_f")] = _witness(
        4, {frozenset(): 3, frozenset({"_other"}): 1}
    )
    (d,) = graftsan.divergence_report(san)
    assert d["kind"] == "static-owned-never-locked"
    assert d["field"] == "owned_f" and d["writes"] == 4


def test_divergence_runtime_locked_not_owned_suggests_pin():
    san = _report_san()
    san.witness.records[("m.C", "quiet_f")] = _witness(
        5, {frozenset({"_mu"}): 5}
    )
    (d,) = graftsan.divergence_report(san)
    assert d["kind"] == "runtime-locked-not-owned"
    assert "# graftlint: owner=_mu" in d["detail"]


def test_divergence_excludes_lock_free_and_unknown_writes():
    san = _report_san()
    # consistently LOCK-FREE writes (set_label's shape): not a missed
    # convention, no divergence
    san.witness.records[("m.C", "free_f")] = _witness(9, {frozenset(): 9})
    # unattributable raw-lock holds: the report never claims what the
    # witness could not prove
    san.witness.records[("m.C", "fuzzy_f")] = _witness(0, {}, unknown=6)
    # owned field whose provable writes DID hold the owner: agreement
    san.witness.records[("m.C", "owned_f")] = _witness(
        3, {frozenset({"_lock"}): 3}
    )
    assert graftsan.divergence_report(san) == []


# -- schedule explorer determinism --------------------------------------------


def test_schedule_decisions_pure_in_seed_site_ordinal():
    a = ScheduleExplorer(None, seed=3)
    b = ScheduleExplorer(None, seed=3)
    seq = [a.decision("wal.append", k) for k in range(256)]
    assert seq == [b.decision("wal.append", k) for k in range(256)]
    # a different seed explores a different interleaving
    c = ScheduleExplorer(None, seed=4)
    assert seq != [c.decision("wal.append", k) for k in range(256)]
    # and different sites decorrelate under one seed
    assert seq != [a.decision("wal.fsync", k) for k in range(256)]
    perturbs = sum(1 for p, _ in seq if p)
    assert 0 < perturbs < 128  # ~p_yield=0.25, never all, never none
    # sleeps stay inside the declared envelope
    assert all(0.0 <= s <= a.max_sleep_us / 1e6 for _, s in seq)


def test_schedule_hook_rides_resilience_sites(armed):
    resilience.checkpoint("test.site.alpha")
    resilience.checkpoint("test.site.alpha")
    resilience.checkpoint("test.site.beta")
    sc = armed.scheduler
    assert sc.site_counts["test.site.alpha"] == 2
    assert sc.site_counts["test.site.beta"] == 1


# -- disabled means free ------------------------------------------------------


def test_disabled_mode_zero_probes_on_cached_program_path(monkeypatch):
    monkeypatch.delenv(graftsan.ENV_ARM, raising=False)
    assert not graftsan.enabled()
    assert graftsan.current() is None

    # warm, cached-program serving traffic with no sanitizer installed
    ctx = sd.TPUOlapContext(sd.SessionConfig.load_calibrated())
    ctx.register_table(
        "ev", _cols(n=500, seed=2),
        dimensions=["city"], metrics=["qty", "rev"],
    )
    q = "SELECT city, SUM(rev) AS r FROM ev GROUP BY city"
    ctx.sql(q)  # compiles
    ctx.sql(q)  # cached path
    assert graftsan.probe_count() == 0

    # structurally unwrapped: the scheduler hook is the None no-op …
    assert resilience._sched_hook is None
    # … CanonicalFold runs its own bytecode …
    assert CanonicalFold.add.__qualname__ == "CanonicalFold.add"
    assert CanonicalFold.drain.__qualname__ == "CanonicalFold.drain"
    # … and NO contract class carries a witness __setattr__/__init__
    with open(CONTRACTS_PATH) as f:
        doc = json.load(f)
    for key in doc["lock_attrs"]:
        modname, _, clsname = key.rpartition(".")
        cls = Sanitizer._import_class(modname, clsname)
        assert cls is not None, key
        assert "san_setattr" not in getattr(
            cls.__dict__.get("__setattr__"), "__qualname__", ""
        ), key
        assert "san_init" not in getattr(
            cls.__dict__.get("__init__"), "__qualname__", ""
        ), key


def test_install_uninstall_roundtrip_restores_classes(monkeypatch):
    monkeypatch.setenv(graftsan.ENV_ARM, "1")
    from spark_druid_olap_tpu.resilience import PartialCollector

    before_setattr = PartialCollector.__dict__.get("__setattr__")
    before_add = CanonicalFold.add
    san = graftsan.install(
        contracts_path=CONTRACTS_PATH, root=ROOT, seed=0
    )
    try:
        wrapped = PartialCollector.__dict__.get("__setattr__")
        assert "san_setattr" in getattr(wrapped, "__qualname__", "")
        assert CanonicalFold.add is not before_add
        # double-install is refused: one sanitizer per process
        with pytest.raises(RuntimeError):
            Sanitizer(san.contracts, ROOT).install()
    finally:
        graftsan.uninstall()
    assert PartialCollector.__dict__.get("__setattr__") is before_setattr
    assert CanonicalFold.add is before_add
    assert graftsan.probe_count() == 0


# -- protocol witness (ISSUE 20): GL28xx/GL29xx enforced live -----------------


def _protocol_contracts():
    """Hand-built table: just the durable-publish machine, two stamp
    sites, and the admission-slot balance probes."""
    from tools.graftlint.contracts import _jsonify
    from tools.graftlint.passes.durability_protocol import (
        DURABLE_PUBLISH,
    )

    return {
        "version": 1,
        "package": "tests",
        "lock_ownership": [],
        "lock_attrs": {},
        "fold_sinks": [],
        "thread_roots": [],
        "allow_sites": [],
        "protocol_automata": [_jsonify(DURABLE_PUBLISH)],
        "effect_sites": {
            "wal.journal_write": "journal",
            "wal.post_fsync_pre_publish": "fsync",
        },
        "protocol_probes": [
            {"module": "spark_druid_olap_tpu.resilience",
             "class": "AdmissionController", "method": "acquire",
             "effect": "acquire"},
            {"module": "spark_druid_olap_tpu.resilience",
             "class": "AdmissionController", "method": "release",
             "effect": "release"},
        ],
    }


@pytest.fixture()
def protocol_san():
    san = Sanitizer(_protocol_contracts(), ROOT, seed=9)
    san.install(schedule=False)
    try:
        yield san
    finally:
        san.uninstall()


def test_correct_publish_order_and_rearming_are_clean(protocol_san):
    """journal -> fsync -> publish satisfies the machine; the next
    journal re-arms it from the accept state for the next operation."""
    for _ in range(2):
        resilience.checkpoint("wal.journal_write")
        resilience.checkpoint("wal.post_fsync_pre_publish")
        protocol_san.protocol.stamp("publish", "catalog.put")
    # an UNARMED publish (no journal in flight) is the ephemeral path:
    # the static later:journal evidence rule maps to arming here
    protocol_san.protocol.stamp("publish", "catalog.put")
    assert protocol_san.violations == []
    assert protocol_san.protocol.stamps == 7


def test_injected_out_of_order_publish_caught_with_replay_seed(
    protocol_san,
):
    resilience.checkpoint("wal.journal_write")  # arms the machine
    with pytest.raises(graftsan.SanitizerViolation) as ei:
        protocol_san.protocol.stamp("publish", "catalog.put")
    msg = str(ei.value)
    assert "GL2801" in msg and "durable-publish" in msg
    # the stamp trail and the exact replay seed ride the message
    assert "journal@wal.journal_write" in msg
    assert f"{graftsan.ENV_SEED}=9" in msg
    assert protocol_san.violations[-1]["kind"] == "protocol"
    # the machine reset: the NEXT correctly-ordered operation is clean
    resilience.checkpoint("wal.journal_write")
    resilience.checkpoint("wal.post_fsync_pre_publish")
    protocol_san.protocol.stamp("publish", "catalog.put")
    assert len(protocol_san.violations) == 1


def test_leaked_admission_slot_caught_by_quiesce_check(protocol_san):
    from spark_druid_olap_tpu.resilience import AdmissionController

    pool = AdmissionController(max_concurrent=2, queue_timeout_ms=50.0)
    assert pool.acquire()
    with pytest.raises(graftsan.SanitizerViolation) as ei:
        protocol_san.protocol.check_leaks()
    msg = str(ei.value)
    assert "GL2901" in msg and "slot" in msg
    assert f"{graftsan.ENV_SEED}=9" in msg
    pool.release()
    protocol_san.protocol.check_leaks()  # balanced: no violation
    # a REJECTED acquire (False) holds nothing and must not count
    a, b = pool.acquire(), pool.acquire()
    assert a and b
    assert pool.acquire() is False  # pool exhausted, times out
    pool.release()
    pool.release()
    protocol_san.protocol.check_leaks()
    assert len(protocol_san.violations) == 1


def test_protocol_hook_chains_behind_scheduler_and_restores(monkeypatch):
    """Full install: the effect stamp chains BEHIND the explorer's
    perturbation hook (both see every site), and uninstall leaves the
    process byte-for-byte unwrapped."""
    monkeypatch.setenv(graftsan.ENV_ARM, "1")
    from spark_druid_olap_tpu.resilience import AdmissionController

    before_acquire = AdmissionController.__dict__["acquire"]
    before_release = AdmissionController.__dict__["release"]
    san = graftsan.install(
        contracts_path=CONTRACTS_PATH, root=ROOT, seed=0
    )
    try:
        assert AdmissionController.__dict__["acquire"] is not before_acquire
        n0 = san.protocol.stamps
        resilience.checkpoint("wal.journal_write")
        assert san.scheduler.site_counts["wal.journal_write"] == 1
        assert san.protocol.stamps == n0 + 1
        # a site with no effect mapping reaches only the explorer
        resilience.checkpoint("engine.batch")
        assert san.protocol.stamps == n0 + 1
    finally:
        graftsan.uninstall()
    assert resilience._sched_hook is None
    assert AdmissionController.__dict__["acquire"] is before_acquire
    assert AdmissionController.__dict__["release"] is before_release
    assert graftsan.probe_count() == 0
