"""Cluster chaos matrix (ISSUE 16): robustness is PROVEN, not assumed.

Every cell runs the same shape: a broker + in-process historicals over
one shared snapshot store, a fault armed at a process-level site (or a
real node shutdown), one or more queries through the loss, and an
assertion about the ANSWER — exact through a replica, coverage-stamped
partial when a whole replica set is gone, never a 500.  The cells:

* kill-a-historical mid-query -> exact answer via its replica
* torn response / RPC failure / slow replica -> failover, exact
* every replica of a segment lost -> coverage-stamped partial
* rolling restart of every historical -> zero failed queries
* WAL-replaying node answers 503 + Retry-After while replicas carry
  traffic, then rejoins with byte-identical answers
* metadata + health serve through any breaker state

The FaultInjector is process-global and the historicals here are
in-process, so `cluster.historical_kill` (fired only inside the
historical's scatter handler) injects into the serving replica while
`cluster.rpc` / `cluster.torn_response` (fired only broker-side)
inject into the broker's RPC path — per-site isolation without
subprocesses.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import spark_druid_olap_tpu as sd
from spark_druid_olap_tpu.cluster import ClusterClient, HistoricalNode
from spark_druid_olap_tpu.resilience import injector

T0 = int(np.datetime64("2023-01-01", "ms").astype(np.int64))
DAY = 86_400_000

Q = (
    "SELECT city, sum(qty) AS q, count(*) AS n "
    "FROM ev GROUP BY city ORDER BY city"
)


@pytest.fixture(autouse=True)
def _disarm():
    injector().disarm()
    yield
    injector().disarm()


def _cols(n=3000, seed=5):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(
            np.array(["austin", "boston", "chicago"], dtype=object), n
        ),
        "qty": rng.integers(1, 100, n).astype(np.int64),
        "ts": T0 + rng.integers(0, 30, n) * DAY,
    }


class _Cluster:
    def __init__(self, d, n_nodes=2, replication=2, n=3000, **cfg_kw):
        cfg_kw.setdefault("cluster_breaker_cooldown_ms", 50.0)
        self.d = str(d)
        self.broker = sd.TPUOlapContext(
            sd.SessionConfig(storage_dir=self.d, **cfg_kw)
        )
        self.broker.register_table(
            "ev", _cols(n), dimensions=["city"], metrics=["qty"],
            time_column="ts", rows_per_segment=800,
        )
        self.nodes = {}
        for i in range(n_nodes):
            h = HistoricalNode(f"h{i}", self.d).start()
            self.nodes[h.node_id] = h
        self.client = ClusterClient(
            self.broker,
            nodes={nid: h.url for nid, h in self.nodes.items()},
            replication=replication,
        ).attach()
        self.client.detach()
        self.oracle = self.broker.sql(Q)
        self.client.attach()
        self._qn = 0

    def query(self):
        """One clustered query, result-cache-proof (distinct no-op
        LIMIT per call)."""
        self._qn += 1
        before = self.client.last_metrics
        df = self.broker.sql(Q + f" LIMIT {200 + self._qn}")
        assert self.client.last_metrics is not before, (
            "query did not scatter"
        )
        return df

    def restart(self, node_id):
        """Kill + reboot one historical (fresh context, fresh port —
        a real process restart re-runs snapshot mmap + WAL replay)."""
        self.nodes[node_id].shutdown()
        h = HistoricalNode(node_id, self.d).start()
        self.nodes[node_id] = h
        self.client.set_node_url(node_id, h.url)
        return h

    def close(self):
        self.client.close()
        for h in self.nodes.values():
            h.shutdown()


@pytest.fixture()
def cluster(tmp_path):
    c = _Cluster(tmp_path)
    yield c
    c.close()


# -- single-fault cells -------------------------------------------------------


def test_kill_historical_mid_query_exact_via_replica(cluster):
    from spark_druid_olap_tpu.obs.registry import get_registry

    fo = get_registry().counter(
        "sdol_cluster_failover_total", labels=("node",)
    )
    base = sum(fo.snapshot().values())
    # the serving replica dies INSIDE its handler; the broker must
    # serve the exact answer through the segment's other replica
    injector().arm("cluster.historical_kill", mode="error", times=1)
    df = cluster.query()
    assert cluster.oracle.equals(df)
    assert not df.attrs.get("partial", False)
    assert sum(fo.snapshot().values()) - base >= 1


def test_torn_response_fails_over_exact(cluster):
    # the broker sees half a response body — the strict wire decode
    # must reject it and fail over, never merge garbage
    injector().arm("cluster.torn_response", mode="partial",
                   fraction=0.5, times=1)
    df = cluster.query()
    assert cluster.oracle.equals(df)
    assert not df.attrs.get("partial", False)


def test_rpc_failures_retry_and_fail_over_exact(cluster):
    injector().arm("cluster.rpc", mode="error", times=2)
    df = cluster.query()
    assert cluster.oracle.equals(df)
    assert not df.attrs.get("partial", False)


def test_slow_replica_still_exact(cluster):
    injector().arm("cluster.rpc", mode="delay", delay_ms=80.0, times=1)
    df = cluster.query()
    assert cluster.oracle.equals(df)
    assert not df.attrs.get("partial", False)


# -- replica-set loss ---------------------------------------------------------


def test_all_replicas_lost_serves_coverage_stamped_partial(tmp_path):
    c = _Cluster(tmp_path, n_nodes=2, replication=1)
    try:
        # replication=1: each segment has exactly one home; killing one
        # node loses its replica SETS outright.  The answer must be a
        # stamped partial over the surviving segments — never an error.
        victim = next(iter(c.client.assignment.segment_map.values()))[0]
        c.nodes[victim].shutdown()
        df = c.query()
        assert df.attrs.get("partial") is True
        assert 0.0 <= df.attrs["coverage"] < 1.0
        m = c.broker.last_metrics
        assert m.partial and m.coverage == df.attrs["coverage"]
        # the survivors' rows are still exact: every (city, q, n) row
        # served must match the oracle's row for that city upper-bounded
        merged = df.merge(c.oracle, on="city", suffixes=("", "_full"))
        assert (merged["q"] <= merged["q_full"]).all()
    finally:
        c.close()


def test_every_node_down_partial_not_500(tmp_path):
    c = _Cluster(tmp_path, n_nodes=2, replication=2)
    try:
        for h in c.nodes.values():
            h.shutdown()
        df = c.query()  # no exception: fully degraded, stamped
        assert df.attrs.get("partial") is True
        assert df.attrs["coverage"] == 0.0
    finally:
        c.close()


def test_health_and_metadata_serve_through_open_breakers(tmp_path):
    from spark_druid_olap_tpu.server import OlapServer

    c = _Cluster(tmp_path, n_nodes=2, replication=2)
    srv = OlapServer(c.broker, port=0).start()
    try:
        for h in c.nodes.values():
            h.shutdown()
        for _ in range(3):  # drive both breakers past the threshold
            c.query()
        st = c.client.state()
        assert any(
            n["breaker"]["state"] == "open" for n in st["nodes"].values()
        )
        assert st["segments_lost"] > 0
        # health and metadata keep serving through ANY breaker state
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/status/health", timeout=30
        ) as r:
            doc = json.loads(r.read())
        assert doc["cluster"]["live"] < 2
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/druid/v2/datasources", timeout=30
        ) as r:
            assert "ev" in json.loads(r.read())
    finally:
        srv.shutdown()
        c.close()


# -- rolling restart ----------------------------------------------------------


def test_rolling_restart_every_historical_zero_failed_queries(cluster):
    """The acceptance cell: restart EVERY historical, one at a time,
    with queries flowing across each step — all exact, none failed,
    none partial."""
    served = 0
    for node_id in sorted(cluster.nodes):
        cluster.nodes[node_id].shutdown()
        for _ in range(2):  # queries through the downtime window
            df = cluster.query()
            assert cluster.oracle.equals(df)
            assert not df.attrs.get("partial", False)
            served += 1
        cluster.restart(node_id)
        time.sleep(0.08)  # let the down-node's breaker cooldown lapse
        for _ in range(2):  # queries after rejoin
            df = cluster.query()
            assert cluster.oracle.equals(df)
            assert not df.attrs.get("partial", False)
            served += 1
    assert served == 4 * len(cluster.nodes)


# -- replay-while-serving (satellite) -----------------------------------------


def test_replaying_node_503s_replicas_carry_then_rejoins_identical(
    cluster,
):
    c = cluster
    h0 = c.nodes["h0"]
    # simulate the WAL-replay boot window: the node is up but its
    # storage is mid-recovery — the scatter surface must refuse with
    # 503 + Retry-After (the broker treats it as a failed replica)
    h0.ctx.storage.replay_in_progress = True
    try:
        req = urllib.request.Request(
            h0.url + "/druid/v2/cluster/partial",
            data=json.dumps({"query": {}}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
        assert float(ei.value.headers["Retry-After"]) > 0
        # its replicas carry the traffic meanwhile: exact, not partial
        df = c.query()
        assert c.oracle.equals(df)
        assert not df.attrs.get("partial", False)
    finally:
        h0.ctx.storage.replay_in_progress = False

    # real rejoin: kill + reboot (snapshot mmap + WAL replay) and
    # rebalance — answers must come back byte-identical
    c.restart("h0")
    c.client.rebalance()
    time.sleep(0.08)
    df = c.query()
    assert c.oracle.to_json() == df.to_json()  # byte-identical
    assert not df.attrs.get("partial", False)


def test_restarted_node_serves_replayed_wal_rows(tmp_path):
    """A historical restarted AFTER the broker flushed new rows boots
    the newer snapshot generation and rejoins at the new version."""
    c = _Cluster(tmp_path, n_nodes=2, replication=2)
    try:
        c.broker.append_rows("ev", _cols(n=400, seed=9))
        c.broker.storage.flush("ev")  # new snapshot generation
        # restart both nodes onto the new generation, then rebalance so
        # the assignment pins the new version + segment set
        for nid in sorted(c.nodes):
            c.restart(nid)
        c.client.rebalance()
        time.sleep(0.08)
        c.client.detach()
        oracle2 = c.broker.sql(Q + " LIMIT 151")
        c.client.attach()
        df = c.query()
        assert oracle2.equals(df)
        assert not df.attrs.get("partial", False)
    finally:
        c.close()


# -- tracing under chaos (ISSUE 19) -------------------------------------------


def _walk_spans(node, out=None):
    out = [] if out is None else out
    out.append(node)
    for c in node.get("children", ()):
        _walk_spans(c, out)
    return out


def _rpc_spans(doc):
    return [
        s for s in _walk_spans(doc["spans"])
        if s.get("name") == "cluster_rpc"
    ]


def _grafts(span):
    return [
        c for c in span.get("children", ())
        if (c.get("attrs") or {}).get("remote")
    ]


def _assert_single_tree(doc):
    """ONE tree: a single `query` root, every span JSON-renderable, and
    every grafted subtree hanging under a cluster_rpc span."""
    assert doc["spans"]["name"] == "query"
    json.dumps(doc)  # renders end-to-end, no cycles/unserializables
    for s in _walk_spans(doc["spans"]):
        if (s.get("attrs") or {}).get("remote"):
            continue  # remote spans carry their own subtree
        for child in _grafts(s):
            assert s["name"] == "cluster_rpc", (
                "graft outside a cluster_rpc span"
            )
            assert child["attrs"].get("node")


def test_trace_kill_mid_query_single_tree_error_span_plus_graft(cluster):
    injector().arm("cluster.historical_kill", mode="error", times=1)
    df = cluster.query()
    assert cluster.oracle.equals(df)
    doc = cluster.broker.tracer.last_trace_dict()
    _assert_single_tree(doc)
    rpcs = _rpc_spans(doc)
    failed = [s for s in rpcs if s["attrs"].get("error")]
    ok = [s for s in rpcs if s["attrs"].get("outcome") == "ok"]
    assert failed, "killed attempt left no error span"
    assert all(not _grafts(s) for s in failed)
    assert ok and any(_grafts(s) for s in ok)
    for g in (g for s in ok for g in _grafts(s)):
        assert g["name"] == "query" and g["attrs"]["node"]


def test_trace_torn_response_failover_still_one_tree(cluster):
    injector().arm("cluster.torn_response", mode="partial",
                   fraction=0.5, times=1)
    df = cluster.query()
    assert cluster.oracle.equals(df)
    doc = cluster.broker.tracer.last_trace_dict()
    _assert_single_tree(doc)
    rpcs = _rpc_spans(doc)
    assert any(s["attrs"].get("error") for s in rpcs)
    assert any(_grafts(s) for s in rpcs)


def test_trace_hedged_rpc_attempts_marked_and_grafted(tmp_path):
    c = _Cluster(tmp_path, cluster_hedge_ms=5.0)
    try:
        injector().arm("cluster.rpc", mode="delay", delay_ms=120.0,
                       times=1)
        df = c.query()
        assert c.oracle.equals(df)
        doc = c.broker.tracer.last_trace_dict()
        _assert_single_tree(doc)
        rpcs = _rpc_spans(doc)
        assert any(s["attrs"].get("hedge") for s in rpcs), (
            "no hedged attempt span recorded"
        )
        assert any(_grafts(s) for s in rpcs)
    finally:
        c.close()


def test_trace_all_replicas_lost_tree_still_well_formed(tmp_path):
    c = _Cluster(tmp_path, n_nodes=2, replication=1)
    try:
        victim = next(iter(c.client.assignment.segment_map.values()))[0]
        c.nodes[victim].shutdown()
        df = c.query()
        assert df.attrs.get("partial") is True
        doc = c.broker.tracer.last_trace_dict()
        _assert_single_tree(doc)
        dead = [
            s for s in _rpc_spans(doc)
            if s["attrs"].get("node") == victim
        ]
        assert dead and all(s["attrs"].get("error") for s in dead)
        assert all(not _grafts(s) for s in dead)
    finally:
        c.close()


def test_trace_absent_graft_degrades_to_untraced_stub(
    cluster, monkeypatch
):
    # the historical computes a good state but ships no trace payload
    # (size cap, defect, old build): the broker grafts an `untraced`
    # stub and keeps per-node attribution via the receipt side-channel
    from spark_druid_olap_tpu.cluster import wire

    monkeypatch.setattr(wire, "encode_trace", lambda doc, **kw: None)
    cluster.broker.tracer.force_sample_next()
    df = cluster.query()
    assert cluster.oracle.equals(df)
    doc = cluster.broker.tracer.last_trace_dict()
    _assert_single_tree(doc)
    ok = [
        s for s in _rpc_spans(doc)
        if s["attrs"].get("outcome") == "ok"
    ]
    assert ok
    stubs = [g for s in ok for g in _grafts(s)]
    assert stubs and all(
        g["attrs"].get("untraced") for g in stubs
    ), "absent trace payload did not degrade to untraced stubs"
    # the separately-shipped receipt keeps per-node buckets flowing
    nodes = doc["receipt"]["cluster"]["nodes"]
    assert any("device_ms" in b for b in nodes.values())


def test_trace_receipt_accounts_90pct_with_per_node_buckets(cluster):
    cluster.broker.tracer.force_sample_next()
    df = cluster.query()
    assert cluster.oracle.equals(df)
    rc = cluster.broker.tracer.last_trace_dict()["receipt"]
    wall = rc["wall_ms"]
    assert wall > 0
    # the ISSUE 19 acceptance bar: >= 90% of wall attributed for a
    # cluster query (grafted subtrees fold per node, rpc overlay spans
    # never double-count against the scatter wall)
    assert rc["unattributed_ms"] <= 0.10 * wall, rc
    nodes = rc["cluster"]["nodes"]
    assert len(nodes) >= 1
    for nid, b in nodes.items():
        assert b["ok"] >= 1, (nid, b)
        assert "device_ms" in b and "transfer_ms" in b, (nid, b)
        assert b["remote_wall_ms"] > 0, (nid, b)


def test_federated_scrape_with_dead_node_stale_never_500(tmp_path):
    from spark_druid_olap_tpu.server import OlapServer

    c = _Cluster(tmp_path, n_nodes=2, replication=2)
    srv = OlapServer(c.broker, port=0).start()
    try:
        c.nodes["h1"].shutdown()
        df = c.query()  # replica carries it; also seeds a trace
        assert c.oracle.equals(df)
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(
            base + "/status/metrics?cluster=1", timeout=30
        ) as r:
            assert r.status == 200
            text = r.read().decode()
        stale = {
            line.split("{node=\"")[1].split("\"")[0]: line.rsplit(" ", 1)[-1]
            for line in text.splitlines()
            if line.startswith("sdol_cluster_scrape_stale{")
        }
        assert stale["h1"] == "1" and stale["h0"] == "0"
        assert 'node="h0"' in text  # live node's series are labeled
        with urllib.request.urlopen(
            base + "/status/profile?cluster=1", timeout=30
        ) as r:
            assert r.status == 200
            prof = json.loads(r.read())
        assert prof["cluster"] is True
        assert prof["stale"] == ["h1"]
        assert prof["nodes"]["h1"] == {"stale": True}
        assert isinstance(prof["nodes"]["h0"], dict)
        # the grafted cluster trace serves as ONE tree over HTTP too
        qid = c.broker.tracer.last_trace_dict()["query_id"]
        with urllib.request.urlopen(
            base + f"/druid/v2/trace/{qid}", timeout=30
        ) as r:
            doc = json.loads(r.read())
        _assert_single_tree(doc)
        assert _rpc_spans(doc)
    finally:
        srv.shutdown()
        c.close()


def test_parallel_scrape_matches_serial_and_propagates_faults():
    """ISSUE 20 satellite: the broker-pooled scrape fan-out answers
    byte-identically to the serial path (sorted submission + sorted
    fold), stamps unreachable nodes stale, and lets an injected fault
    at `cluster.federate` propagate out of `Future.result()` instead of
    being swallowed as staleness."""
    import http.server
    from concurrent.futures import ThreadPoolExecutor

    from spark_druid_olap_tpu.cluster.federation import (
        merge_prometheus,
        scrape_nodes,
    )
    from spark_druid_olap_tpu.resilience import InjectedFault

    class _H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = (
                "# HELP m x\n# TYPE m counter\n"
                f"m{{port=\"{self.server.server_address[1]}\"}} 1\n"
            ).encode()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    servers, nodes = [], {}
    for i in range(3):
        s = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _H)
        threading.Thread(target=s.serve_forever, daemon=True).start()
        servers.append(s)
        nodes[f"h{i}"] = f"http://127.0.0.1:{s.server_address[1]}"
    nodes["zz-dead"] = "http://127.0.0.1:9"  # refused -> stale stamp
    pool = ThreadPoolExecutor(max_workers=4)
    try:
        serial = scrape_nodes(nodes, "/status/metrics", 2.0)
        par = scrape_nodes(nodes, "/status/metrics", 2.0, pool=pool)
        assert list(par) == list(serial) == sorted(nodes)
        assert par == serial
        assert merge_prometheus(dict(par)) == merge_prometheus(
            dict(serial)
        )
        assert par["zz-dead"] is None and par["h0"] is not None

        injector().arm("cluster.federate", mode="error", times=1)
        with pytest.raises(InjectedFault):
            scrape_nodes(nodes, "/status/metrics", 2.0, pool=pool)
    finally:
        injector().disarm()
        pool.shutdown(wait=False)
        for s in servers:
            s.shutdown()
