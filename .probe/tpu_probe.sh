#!/bin/bash
# Persistent TPU probe: retry until the tunnel answers, then exit 0.
LOG=/root/repo/.probe/probe.log
for i in $(seq 1 500); do
  ts=$(date -u +%FT%TZ)
  out=$(timeout 90 python -c "import jax; d=jax.devices()[0]; print(d.platform, d)" 2>&1 | tail -1)
  if echo "$out" | grep -qi "tpu"; then
    echo "$ts attempt=$i SUCCESS: $out" >> "$LOG"
    exit 0
  fi
  echo "$ts attempt=$i fail: ${out:0:200}" >> "$LOG"
  sleep 240
done
exit 1
